"""The server: replicated state + leader-only scheduling subsystems.

Reference: nomad/server.go (wiring), nomad/leader.go:224
establishLeadership (broker/plan-queue/blocked-evals/heartbeat lifecycle),
nomad/node_endpoint.go (node RPCs incl. createNodeEvals :495),
nomad/job_endpoint.go (job register/deregister), nomad/eval_endpoint.go.

Round-1 scope: single process, single "region"; every mutation flows
through raft_apply so Phase 2 can drop in real replication. The endpoint
methods here are what the RPC layer (and the HTTP API above it) call.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from .. import blackbox, metrics, trace
from ..scheduler.context import SchedulerConfig
from ..state import StateStore
from ..state.events import wire_events
from ..stream import EventBroker
from ..structs import (
    Allocation,
    DrainStrategy,
    Evaluation,
    Job,
    generate_uuid,
    now_ns,
)
from ..structs.structs import (
    ALLOC_CLIENT_STATUS_FAILED,
    DEFAULT_NAMESPACE,
    EVAL_STATUS_BLOCKED,
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_DRAIN,
    EVAL_TRIGGER_NODE_UPDATE,
    EVAL_TRIGGER_RETRY_FAILED_ALLOC,
    JOB_TYPE_CORE,
    JOB_TYPE_SERVICE,
    NODE_STATUS_DOWN,
    NODE_STATUS_READY,
)
from .blocked_evals import BlockedEvals
from .core_sched import core_eval
from .deployment_watcher import DeploymentsWatcher
from .drainer import NodeDrainer
from .eval_broker import EvalBroker
from .heartbeat import HeartbeatWheel
from .periodic import PeriodicDispatch
from .plan_apply import PlanApplier
from .plan_queue import PlanQueue
from .raft import FSM, InmemLog
from .volume_watcher import VolumeWatcher
from .watch_hub import AllocWatchHub
from .worker import TPUBatchWorker, Worker

logger = logging.getLogger("nomad_tpu.server")


class ConflictError(Exception):
    """An expected operational rejection (HTTP 400-class), e.g. re-running
    ACL bootstrap. Distinct from PermissionError so filesystem EACCES
    never masquerades as a client error."""


class _RegisterBox:
    """One submitted registration's completion slot."""

    __slots__ = ("event", "error", "fallback")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.error: Optional[BaseException] = None
        self.fallback = False


class NodeRegisterBatcher:
    """Coalesces concurrent Node.register writes into shared
    ``node_register_batch`` raft entries.

    A mass reconnect (partition heals, fleet restart) lands thousands of
    registrations in a few seconds; committing each as its own raft
    entry serializes the storm through the log at one fsync-equivalent
    apiece. The batcher holds each registration for a ~5ms coalescing
    window and commits everything that arrived as ONE entry (bounded at
    ``max_batch``), so the log cost of a reconnect storm is
    O(storm / batch) instead of O(storm). Callers still block until
    their batch commits — acknowledgement semantics are unchanged.

    Leader-only lifecycle: started at establish-leadership, stopped at
    revoke. ``submit`` returns False when not running (caller falls back
    to a direct ``node_register`` apply) so followers applying forwarded
    writes and pre-leadership tests never deadlock on a dead worker.
    """

    def __init__(
        self, raft_apply, window_s: float = 0.005, max_batch: int = 256
    ) -> None:
        self.raft_apply = raft_apply
        self.window_s = window_s
        self.max_batch = max_batch
        self._cv = threading.Condition(threading.Lock())
        self._queue: list[tuple[object, _RegisterBox]] = []
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        with self._cv:
            if self._running:
                return
            self._running = True
            self._thread = threading.Thread(
                target=self._run, name="node-register-batcher", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        with self._cv:
            if not self._running:
                return
            self._running = False
            drained, self._queue = self._queue, []
            thread, self._thread = self._thread, None
            self._cv.notify_all()
        # anything still queued at revoke-leadership falls back to the
        # caller's direct apply path (which will fail NotLeader exactly
        # as an unbatched write would have)
        for _node, box in drained:
            box.fallback = True
            box.event.set()
        if thread is not None:
            thread.join(timeout=5)

    def submit(self, node) -> bool:
        """Queue a registration and block until its batch commits.
        True = committed via a batch entry; False = batcher not running,
        caller must apply directly. Re-raises the batch's raft error."""
        with self._cv:
            if not self._running:
                return False
            box = _RegisterBox()
            self._queue.append((node, box))
            self._cv.notify()
        box.event.wait()
        if box.fallback:
            return False
        if box.error is not None:
            raise box.error
        return True

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._running and not self._queue:
                    self._cv.wait()
                if not self._running:
                    return
            # coalescing window: let the rest of a concurrent burst
            # arrive before cutting the batch (no locks held)
            time.sleep(self.window_s)
            with self._cv:
                batch = self._queue[: self.max_batch]
                del self._queue[: self.max_batch]
            if not batch:
                continue
            nodes = [node for node, _box in batch]
            err: Optional[BaseException] = None
            try:
                self.raft_apply("node_register_batch", nodes)
            except BaseException as exc:  # propagate to every waiter
                err = exc
            else:
                metrics.incr("nomad.fleet.node_raft_batches")
                metrics.incr(
                    "nomad.fleet.node_raft_coalesced", len(nodes)
                )
            for _node, box in batch:
                box.error = err
                box.event.set()


class Server:
    def __init__(
        self,
        num_workers: int = 2,
        scheduler_config: Optional[SchedulerConfig] = None,
        use_tpu_batch_worker: bool = False,
        enabled_schedulers: Optional[list[str]] = None,
    ) -> None:
        """enabled_schedulers — which eval types this server's workers
        serve (reference EnabledSchedulers, nomad/config.go:159 consumed
        by worker.go:146; num_workers is NumSchedulers). None = all
        types. An operator shards scheduler load by giving servers
        disjoint type lists — e.g. a server with ["sysbatch"] dedicates
        its whole pool to sysbatch evals. The _core GC type is always
        served (the reference appends it implicitly)."""
        self.state = StateStore()
        self.fsm = FSM(self.state)
        self.log = InmemLog(self.fsm)
        # Event stream backbone (reference nomad/stream/event_broker.go,
        # wired from state txns via nomad/state/events.go).
        self.event_broker = EventBroker()
        wire_events(self.state, self.event_broker)
        self.scheduler_config = scheduler_config or SchedulerConfig()

        self.eval_broker = EvalBroker()
        self.plan_queue = PlanQueue()
        # Telemetry providers: live subsystem stats sampled at /v1/metrics
        # snapshot time (reference nomad/server.go:444-450 publishes the
        # same broker/plan-queue gauges on a timer).
        self._metric_handles = [
            # live depths + shed counters computed under the broker lock
            # (the legacy stats dict only ever tracked dead-letters)
            ("nomad.broker", metrics.register_provider(
                "nomad.broker", lambda: self.eval_broker.stats_snapshot()
            )),
            ("nomad.plan_queue", metrics.register_provider(
                "nomad.plan_queue", lambda: {"depth": self.plan_queue.depth()}
            )),
            # worker-pool utilization for `operator top`: pool size and
            # total evals processed (throughput = its rate)
            ("nomad.workers", metrics.register_provider(
                "nomad.workers", self._worker_stats
            )),
            # blocked-evals storm containment gauges (dedup + cap)
            ("nomad.blocked_evals", metrics.register_provider(
                "nomad.blocked_evals",
                lambda: dict(self.blocked_evals.stats),
            )),
            # heartbeat wheel depth (armed TTLs + live buckets)
            ("nomad.heartbeat", metrics.register_provider(
                "nomad.heartbeat", lambda: self.heartbeaters.stats()
            )),
            # fleet panel: watch fan-out + node liveness census
            ("nomad.fleet", metrics.register_provider(
                "nomad.fleet", self._fleet_stats
            )),
            # event-stream subscriber census (bounded-queue discipline)
            ("nomad.stream", metrics.register_provider(
                "nomad.stream", lambda: self.event_broker.stats()
            )),
        ]
        self.plan_applier = PlanApplier(
            self.plan_queue, self.state, self.raft_apply, self.raft_apply_async
        )
        self.blocked_evals = BlockedEvals(self._requeue_unblocked)
        # Sharded heartbeat timer wheel (heartbeat.py): one ticker
        # thread, O(1) re-arm, and expiry storms delivered as ONE batch
        # per sweep so a mass expiry commits a bounded number of raft
        # entries instead of one per node.
        self.heartbeaters = HeartbeatWheel(
            self._invalidate_heartbeat,
            on_expire_batch=self._invalidate_heartbeat_batch,
        )
        self.heartbeaters.node_count_fn = lambda: len(self.state.nodes())
        # Event-driven alloc-watch fan-out (watch_hub.py): blocking
        # client alloc watches wake per-node instead of per-write.
        # Constructed here (not at establish-leadership) because
        # followers serve Node.get_client_allocs from their replicas.
        self.watch_hub = AllocWatchHub(self.state)
        # Mass-reconnect registration coalescer: concurrent
        # Node.register writes share node_register_batch raft entries
        # (leader-only; started at establish-leadership).
        self.register_batcher = NodeRegisterBatcher(self.raft_apply)
        self.deployment_watcher = DeploymentsWatcher(self.state, self.raft_apply)
        self.drainer = NodeDrainer(self.state, self.raft_apply)
        self.volume_watcher = VolumeWatcher(self.state, self.raft_apply)
        self.periodic = PeriodicDispatch(self.state, self.raft_apply)
        # Threshold GC cadence (reference leader.go schedulePeriodic: one
        # timer per GC kind, 5m default).
        self.gc_interval_s = 300.0
        self._gc_stop = threading.Event()
        self._gc_thread: Optional[threading.Thread] = None

        all_types = ["service", "batch", "system", "sysbatch"]
        if enabled_schedulers is None:
            enabled = list(all_types)
        else:
            unknown = set(enabled_schedulers) - set(all_types)
            if unknown:
                raise ValueError(
                    f"enabled_schedulers: unknown types {sorted(unknown)}"
                )
            enabled = [t for t in all_types if t in enabled_schedulers]
        self.enabled_schedulers = enabled
        serve = enabled + [JOB_TYPE_CORE]
        self.workers: list[Worker] = []
        self.tpu_worker: Optional[TPUBatchWorker] = None
        batchable = [t for t in ("service", "batch") if t in enabled]
        if use_tpu_batch_worker and batchable:
            self.tpu_worker = TPUBatchWorker(
                self, schedulers=batchable, config=self.scheduler_config
            )
            system_worker = Worker(
                self,
                [t for t in ("system", "sysbatch") if t in enabled]
                + [JOB_TYPE_CORE],
                self.scheduler_config, name="worker-system",
            )
            self.workers.append(system_worker)
        else:
            for i in range(num_workers):
                self.workers.append(
                    Worker(
                        self,
                        list(serve),
                        self.scheduler_config,
                        name=f"worker-{i}",
                    )
                )

        # Token→ACL resolution cache, invalidated by acl table index
        # (reference nomad/acl.go aclCache).
        self._acl_cache: dict[str, tuple[int, object, int]] = {}
        self._acl_bootstrap_lock = threading.Lock()

        # Single writer draining unblocked-eval re-queues (see
        # _requeue_unblocked for why this must be async).
        import queue as _queue

        self._unblock_q: "_queue.Queue" = _queue.Queue()
        self._unblock_thread = threading.Thread(
            target=self._unblock_writer, daemon=True, name="unblock-writer"
        )
        self._unblock_thread.start()

        # FSM side-channels (reference fsm.go:746)
        self.fsm.on_eval_update = self._on_eval_update
        self.fsm.on_node_update = self._on_node_update
        self.fsm.on_alloc_client_update = self._on_alloc_client_update
        self.fsm.on_job_upsert = self._on_job_upsert
        self.fsm.on_volume_release = self.blocked_evals.unblock_all
        self._leader = False
        # Replicated deployments install a replay barrier (cluster.py →
        # RaftNode.wait_for_replay): establish_leadership must not
        # rebuild broker state from a MID-REPLAY store or it re-enqueues
        # evaluations whose plans are still in the unapplied log tail —
        # the scheduler would then re-place them (duplicate allocs).
        # None (single-node InmemLog) ⇒ state is applied synchronously,
        # nothing to wait for.
        self.replay_barrier: Optional[object] = None

    # -- lifecycle -----------------------------------------------------

    def establish_leadership(self) -> None:
        """Enable leader-only subsystems (reference leader.go:224).

        The replay barrier runs FIRST: on a replicated server nothing
        leader-only comes up until the local FSM has applied this
        leadership's own barrier entry (reference leader.go Barrier).
        Without it, subsystems start against a MID-REPLAY store — a
        pending eval from the unapplied tail gets scheduled against
        state that lacks its job's existing allocs and mints duplicates
        (the load-flaky full-cluster-restart failure). Side channels are
        also gated by _leader, so entries applied during the wait are
        silently skipped and then swept up by the post-barrier
        _restore_evals / subsystem starts, which all read the now-
        caught-up store."""
        caught_up = True
        if self.replay_barrier is not None:
            try:
                caught_up = self.replay_barrier()
            except Exception:
                logger.exception("replay barrier failed")
                caught_up = False
        if not caught_up:
            # Deposed during the wait (a revoke is queued right behind
            # this event) — still enable everything so the transitions
            # stay strictly alternating, but don't trust the state for
            # eval restore; the next leader restores instead.
            logger.warning(
                "establishing leadership without a caught-up log "
                "(leadership churn during recovery)"
            )
        self.eval_broker.set_enabled(True)
        self.plan_queue.set_enabled(True)
        self.blocked_evals.set_enabled(True)
        self.heartbeaters.set_enabled(True)
        self.register_batcher.start()
        self.plan_applier.start()
        for w in self.workers:
            w.start()
        if self.tpu_worker:
            self.tpu_worker.start()
        self.deployment_watcher.start()
        self.drainer.start()
        self.volume_watcher.start()
        self.periodic.start()
        # Fresh Event per incarnation (see Worker.start): a thread that
        # outlives join(timeout) polls its own event and still exits.
        self._gc_stop = threading.Event()
        self._gc_thread = threading.Thread(
            target=self._gc_loop, args=(self._gc_stop,), daemon=True,
            name="gc-scheduler"
        )
        self._gc_thread.start()
        self._leader = True
        if caught_up:
            self._restore_evals()
            # Arm a liveness TTL for every node we believe is alive
            # (reference heartbeat.go initializeHeartbeatTimers): node
            # TTL timers are leader-local state and died with the old
            # leader — without re-arming, a client that crashed during
            # the leadership transition would NEVER be marked down and
            # its allocations would stay stranded on a dead node. Live
            # nodes simply re-arm on their next heartbeat.
            try:
                self.heartbeaters.initialize(
                    n.id
                    for n in self.state.nodes()
                    if n.status != NODE_STATUS_DOWN
                )
            except Exception:
                logger.exception("heartbeat timer initialization failed")
        # Bootstrap the default namespace (reference leader.go
        # establishLeadership creates it so it always lists).
        try:
            self._ensure_namespace(DEFAULT_NAMESPACE)
        except Exception:
            logger.exception("default namespace bootstrap failed")

    def revoke_leadership(self) -> None:
        self._leader = False
        self._gc_stop.set()
        if self._gc_thread:
            self._gc_thread.join(timeout=5)
            self._gc_thread = None
        self.deployment_watcher.stop()
        self.drainer.stop()
        self.volume_watcher.stop()
        self.periodic.stop()
        for w in self.workers:
            w.stop()
        for w in self.workers:
            w.join(timeout=5)
        if self.tpu_worker:
            self.tpu_worker.stop()
        self.plan_applier.stop()
        self.register_batcher.stop()
        self.eval_broker.set_enabled(False)
        self.plan_queue.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self.heartbeaters.set_enabled(False)

    def shutdown(self) -> None:
        for name, handle in self._metric_handles:
            metrics.unregister_provider(name, handle)
        self.revoke_leadership()
        self.watch_hub.stop()
        self._unblock_q.put(None)

    def _fleet_stats(self) -> dict[str, float]:
        """`nomad.fleet.*` provider gauges: watch fan-out census plus a
        node-liveness breakdown (the `operator top` Fleet row)."""
        stats = self.watch_hub.stats()
        ready = down = 0
        for node in self.state.nodes():
            if node.status == NODE_STATUS_READY:
                ready += 1
            elif node.status == NODE_STATUS_DOWN:
                down += 1
        stats["nodes_ready"] = ready
        stats["nodes_down"] = down
        return stats

    def _worker_stats(self) -> dict[str, float]:
        workers = list(self.workers)
        processed = sum(w.processed for w in workers)
        count = len(workers)
        if self.tpu_worker is not None:
            processed += self.tpu_worker.processed
            count += 1
        return {"count": float(count), "processed": float(processed)}

    def _restore_evals(self) -> None:
        """Broker state is not persisted; rebuild from the state store
        (reference leader.go:495 restoreEvals). Idempotent across
        leadership churn: an eval the broker already tracks (enqueued by
        an FSM side-channel while the replay barrier was waiting, or by
        a previous establishment this incarnation) is skipped, so
        restore can run any number of times without double-queueing."""
        for ev in self.state.evals():
            if ev.status == EVAL_STATUS_PENDING:
                if not self.eval_broker.tracks(ev.id):
                    self.eval_broker.enqueue(ev)
            elif ev.status == EVAL_STATUS_BLOCKED:
                self.blocked_evals.block(ev)

    # -- raft ----------------------------------------------------------

    def set_raft_applier(self, applier, applier_async=None) -> None:
        """Swap the single-node InmemLog for a replicated log (the cluster
        layer installs RaftNode.apply). Every subsystem routes through
        raft_apply, so nothing else changes. applier_async is the
        submit-without-waiting variant the plan applier pipelines on."""
        self._raft_applier = applier
        self._raft_applier_async = applier_async

    def raft_apply(self, msg_type: str, payload) -> int:
        applier = getattr(self, "_raft_applier", None)
        # the trace's terminal hop: broker dequeue → ... → raft apply
        # (trace.span no-ops on an untraced thread)
        with trace.span(trace.current(), "raft.apply", type=msg_type):
            if applier is not None:
                return applier(msg_type, payload)
            return self.log.apply(msg_type, payload)

    def raft_apply_async(self, msg_type: str, payload):
        """Submit a raft entry and return (index, wait_fn) without
        blocking on the commit."""
        applier = getattr(self, "_raft_applier_async", None)
        if applier is not None:
            return applier(msg_type, payload)
        return self.log.apply_async(msg_type, payload)

    # -- FSM side channels --------------------------------------------

    def _on_eval_update(self, evals: list[Evaluation]) -> None:
        if not self._leader:
            return
        for ev in evals:
            if ev.should_enqueue():
                self.eval_broker.enqueue(ev)
            elif ev.should_block():
                self.blocked_evals.block(ev)

    def _on_node_update(self, node) -> None:
        if not self._leader or node is None:
            return
        # capacity may have appeared: unblock evals for this class
        if node.status == NODE_STATUS_READY:
            self.blocked_evals.unblock(
                node.computed_class, self.state.latest_index()
            )

    def _on_alloc_client_update(self, allocs) -> None:
        if not self._leader:
            return
        # terminal allocs free capacity on their node's class
        for alloc in allocs:
            if alloc.client_terminal_status():
                node = self.state.node_by_id(alloc.node_id)
                if node is not None:
                    self.blocked_evals.unblock(
                        node.computed_class, self.state.latest_index()
                    )

    def _requeue_unblocked(self, ev: Evaluation) -> None:
        """Write an unblocked eval back to pending.

        MUST be asynchronous: this fires from FSM side-channels, i.e. from
        inside the raft apply loop — a synchronous raft_apply here would
        block the apply thread on a commit that needs the apply thread
        (the reference's BlockedEvals likewise hands unblocks to the
        broker via a channel, never re-entering Raft from the FSM). A
        single writer thread drains the queue so a mass unblock (drain
        ending, big node joining) costs one thread, not hundreds."""
        self._unblock_q.put(ev)

    def _unblock_writer(self) -> None:
        while True:
            ev = self._unblock_q.get()
            if ev is None:
                return
            try:
                self.raft_apply("eval_update", [ev])
            except Exception:
                # Lost leadership mid-unblock: the new leader rebuilds
                # blocked-eval state from the store (restoreEvals).
                logger.debug("requeue of unblocked eval %s dropped", ev.id)

    def _on_job_upsert(self, job, ns_id) -> None:
        """Keep the periodic dispatcher's tracked set in sync with the FSM
        (reference fsm.go ApplyJobRegister -> periodicDispatcher.Add)."""
        if not self._leader:
            return
        if job is None:
            self.periodic.remove(*ns_id)
        else:
            self.periodic.add(job)

    # -- job endpoint --------------------------------------------------

    def apply_memory_oversubscription_gate(self, job: Job) -> None:
        """Strip memory_max unless the scheduler config enables it
        (reference: Register gates MemoryMaxMB) — register AND plan
        must apply the same gate or plan diffs lie about destructive
        updates."""
        if not self.scheduler_config.memory_oversubscription:
            for tg in job.task_groups:
                for task in tg.tasks:
                    task.resources.memory_max_mb = 0

    def validate_job_submission(self, job: Job) -> Job:
        """The full register-time validation front-half on a COPY:
        canonicalize, struct validation, oversubscription gate, vault
        allowlist, scaling bounds. One implementation serves register
        AND /v1/validate/job, so the two can never drift."""
        job = job.copy()
        job.canonicalize()
        # Connect admission: inject sidecar tasks/ports/mesh services
        # BEFORE validation so the injected pieces are validated too
        # (reference job_endpoint_hooks.go:60 jobConnectHook).
        from ..connect import inject_connect_sidecars

        inject_connect_sidecars(job)
        job.validate()
        self.apply_memory_oversubscription_gate(job)
        # Fail fast on vault policies outside the operator allowlist
        # (reference job_endpoint.go Register → validateJob vault check);
        # derive_task_token re-checks at mint time.
        for tg in job.task_groups:
            for task in tg.tasks:
                if task.vault:
                    self._check_vault_policies(
                        list(task.vault.get("policies", []))
                    )
            # scaling stanza sanity at SUBMIT time (reference
            # ScalingPolicy.Validate): a min>max or out-of-bounds count
            # would make the group permanently unscalable
            sc = tg.scaling
            if sc is not None and sc.enabled:
                if sc.min < 0 or (sc.max and sc.max < sc.min):
                    raise ValueError(
                        f"group {tg.name!r}: scaling bounds invalid "
                        f"(min {sc.min}, max {sc.max})"
                    )
                if tg.count < sc.min or (sc.max and tg.count > sc.max):
                    raise ValueError(
                        f"group {tg.name!r}: count {tg.count} outside "
                        f"scaling bounds [{sc.min}, {sc.max}]"
                    )
        return job

    def check_eval_admission(self, namespace: str) -> None:
        """Front-door overload guard for the eval-minting write
        endpoints — called directly by job_register (which also covers
        scale and revert, since both re-register), job_force_evaluate,
        job_dispatch, and the Job.periodic_force endpoint: when the broker's
        admission depth or the namespace's fairness cap is exhausted,
        reject BEFORE raft with a retry hint — the HTTP layer maps
        BrokerSaturatedError to 429 + Retry-After, and the RPC string
        form round-trips through the leader-forwarding path. Reads of
        any kind, deregisters (shedding a stop would strand capacity),
        and internal producers are never guarded here; the broker's own
        per-eval admission covers those."""
        sat = self.eval_broker.saturation(namespace)
        if sat is None:
            return
        reason, retry_after = sat
        metrics.incr("nomad.broker.rejected")
        from ..ratelimit import BrokerSaturatedError

        raise BrokerSaturatedError(
            f"eval broker saturated ({reason}: "
            f"{self.eval_broker.pending_count()} pending)",
            retry_after_s=retry_after,
        )

    def job_register(self, job: Job) -> str:
        """Returns the created eval id (reference job_endpoint.go:80)."""
        self.check_eval_admission(job.namespace)
        job = self.validate_job_submission(job)
        self._ensure_namespace(job.namespace)
        if job.is_periodic():
            # A malformed cron spec must be rejected at the API, not fire
            # wild from the dispatcher (reference periodic.go Add validates).
            import time as _time

            from .periodic import next_launch

            next_launch(job.periodic, _time.time())
        ev = None
        if not job.is_periodic() and not job.is_parameterized():
            ev = Evaluation(
                id=generate_uuid(),
                namespace=job.namespace,
                priority=job.priority,
                type=job.type,
                triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                job_id=job.id,
                status=EVAL_STATUS_PENDING,
                create_time=now_ns(),
                modify_time=now_ns(),
            )
        self.raft_apply("job_register", (job, ev))
        return ev.id if ev else ""

    # -- namespace endpoint --------------------------------------------

    def namespace_upsert(self, ns) -> None:
        """Reference: nomad/namespace_endpoint.go UpsertNamespaces."""
        ns.validate()
        self.raft_apply("namespace_upsert", ns)

    def namespace_delete(self, name: str) -> None:
        # pre-validate against current state for a friendly error; the
        # FSM re-checks under the raft serialization point
        if name == DEFAULT_NAMESPACE:
            raise ValueError("the default namespace cannot be deleted")
        if self.state.namespace_by_name(name) is None:
            raise KeyError(f"namespace {name} not found")
        # The replicated apply loop logs-and-continues on FSM errors, so
        # the user-facing in-use refusal must happen here; the store
        # re-checks authoritatively under the raft serialization point.
        # Terminal jobs pending GC don't count (same rule as the store).
        from ..structs.structs import JOB_STATUS_DEAD

        in_use = sum(
            1
            for j in self.state.jobs(name)
            if not (j.stop or j.status == JOB_STATUS_DEAD)
        ) + len(self.state.volumes(name))
        if in_use:
            raise ValueError(f"namespace {name} has {in_use} jobs/volumes")
        self.raft_apply("namespace_delete", name)

    def _ensure_namespace(self, namespace: str) -> None:
        """Writes into a namespace require it to exist (reference
        job_endpoint.go Register's namespace check). 'default' always
        exists — bootstrapped on first use."""
        if namespace == DEFAULT_NAMESPACE:
            if self.state.namespace_by_name(namespace) is None:
                from ..structs.structs import Namespace

                self.raft_apply(
                    "namespace_upsert",
                    Namespace(name=DEFAULT_NAMESPACE,
                              description="Default shared namespace"),
                )
            return
        if self.state.namespace_by_name(namespace) is None:
            raise ValueError(f"namespace {namespace!r} does not exist")

    # -- volume endpoint -----------------------------------------------

    def validate_volume(self, vol) -> None:
        """Shared register/create validation — create must run this
        BEFORE provisioning, or a rejected register would orphan the
        freshly provisioned external storage."""
        if not vol.id or not vol.name:
            raise ValueError("volume requires id and name")
        from ..structs.structs import (
            VOLUME_ACCESS_MULTI_WRITER,
            VOLUME_ACCESS_READ_ONLY,
            VOLUME_ACCESS_SINGLE_WRITER,
        )

        valid_modes = (
            VOLUME_ACCESS_SINGLE_WRITER,
            VOLUME_ACCESS_MULTI_WRITER,
            VOLUME_ACCESS_READ_ONLY,
        )
        if vol.access_mode not in valid_modes:
            # a typo'd mode would silently behave as multi-writer
            raise ValueError(
                f"invalid access_mode {vol.access_mode!r}; "
                f"one of {', '.join(valid_modes)}"
            )

    def volume_register(self, vol) -> None:
        """Register (or update) a volume; claims survive updates
        (reference csi_endpoint.go Register, reshaped for host volumes)."""
        self.validate_volume(vol)
        self._ensure_namespace(vol.namespace)
        self.raft_apply("volume_register", vol)

    def volume_deregister(self, namespace: str, vol_id: str) -> None:
        vol = self.state.volume_by_id(namespace, vol_id)
        if vol is None:
            raise KeyError(f"volume {vol_id} not found")
        if vol.claims:
            raise ValueError(
                f"volume {vol_id} has {len(vol.claims)} active claims"
            )
        self.raft_apply("volume_deregister", (namespace, vol_id))

    # -- secrets (the embedded Vault analog) ---------------------------

    def secret_upsert(self, entry) -> None:
        if not entry.path or not entry.path.strip("/"):
            raise ValueError("secret requires a path")
        self.raft_apply("secret_upsert", entry)

    def secret_delete(self, namespace: str, path: str) -> None:
        if self.state.secret_by_path(namespace, path) is None:
            raise KeyError(f"secret {path} not found")
        self.raft_apply("secret_delete", (namespace, path))

    DERIVED_TOKEN_TTL_S = 3600.0
    # Operator allowlist for task-derivable policies (reference:
    # vault stanza allowed_policies validation in nomad/vault.go — a job
    # may only ask for policies the operator pre-approved; None = no
    # restriction, matching the reference's default). Without this a
    # submit-job token could mint itself any policy via a vault stanza.
    vault_allowed_policies: Optional[list[str]] = None

    def _check_vault_policies(self, policies: list[str]) -> None:
        if self.vault_allowed_policies is None:
            return
        denied = [
            p for p in policies if p not in self.vault_allowed_policies
        ]
        if denied:
            raise PermissionError(
                f"vault policies not in the operator allowlist: {denied}"
            )

    def derive_task_token(self, alloc_id: str, task_name: str) -> dict:
        """Mint a TTL'd ACL token scoped to the task's vault.policies
        (reference nomad/vault.go DeriveVaultToken via the Vault server;
        here the token is a first-class cluster token the client renews).
        Returns {"secret_id", "accessor_id", "ttl_s"}."""
        from ..acl.structs import ACLToken

        alloc = self.state.alloc_by_id(alloc_id)
        if alloc is None:
            raise KeyError(f"alloc {alloc_id} not found")
        if alloc.terminal_status():
            raise ValueError(f"alloc {alloc_id} is terminal")
        job = alloc.job or self.state.job_by_id(alloc.namespace, alloc.job_id)
        tg = job.lookup_task_group(alloc.task_group) if job else None
        task = tg.lookup_task(task_name) if tg else None
        if task is None:
            raise KeyError(f"task {task_name} not in alloc {alloc_id}")
        policies = list((task.vault or {}).get("policies", []))
        self._check_vault_policies(policies)
        token = ACLToken.new(
            name=f"task-{alloc_id[:8]}-{task_name}", policies=policies
        )
        token.expiration_time_ns = now_ns() + int(
            self.DERIVED_TOKEN_TTL_S * 1e9
        )
        self.raft_apply("acl_token_upsert", [token])
        return {
            "secret_id": token.secret_id,
            "accessor_id": token.accessor_id,
            "ttl_s": self.DERIVED_TOKEN_TTL_S,
        }

    def renew_task_token(self, accessor_id: str) -> float:
        """Extend a derived token's TTL (reference vaultclient
        RenewToken → Vault lease renewal)."""
        token = self.state.acl_token_by_accessor(accessor_id)
        if token is None:
            raise KeyError("token not found")
        if not token.expiration_time_ns:
            raise ValueError("token has no TTL")
        if token.expiration_time_ns < now_ns():
            raise ValueError("token already expired")
        renewed = token.copy()
        renewed.expiration_time_ns = now_ns() + int(
            self.DERIVED_TOKEN_TTL_S * 1e9
        )
        self.raft_apply("acl_token_upsert", [renewed])
        return self.DERIVED_TOKEN_TTL_S

    def services_register(self, regs: list) -> None:
        """Upsert service registrations (reference:
        service_registration_endpoint.go Upsert). The owning alloc must
        exist — a late register from a restarting client for a GC'd alloc
        would otherwise resurrect a ghost instance."""
        for reg in regs:
            if not reg.id or not reg.service_name or not reg.alloc_id:
                raise ValueError(
                    "service registration requires id, service_name, alloc_id"
                )
            alloc = self.state.alloc_by_id(reg.alloc_id)
            if alloc is None:
                raise KeyError(f"alloc {reg.alloc_id} not found")
            if alloc.terminal_status():
                # a late check-status upsert must not resurrect rows the
                # service GC just swept
                raise ValueError(f"alloc {reg.alloc_id} is terminal")
        self.raft_apply("service_upsert", regs)

    def services_deregister_alloc(self, alloc_id: str) -> int:
        return self.raft_apply("service_delete_alloc", [alloc_id])

    def services_deregister(self, ids: list[str]) -> int:
        return self.raft_apply("service_delete", ids)

    def alloc_stop(self, alloc_id: str) -> str:
        """Stop one allocation and let the scheduler replace it
        (reference alloc_endpoint.go Stop: DesiredTransition.Migrate +
        an eval). Returns the eval id."""
        from ..structs.structs import DesiredTransition

        alloc = self.state.alloc_by_id(alloc_id)
        if alloc is None:
            raise KeyError(f"alloc {alloc_id} not found")
        job = alloc.job or self.state.job_by_id(alloc.namespace, alloc.job_id)
        ev = Evaluation(
            id=generate_uuid(),
            namespace=alloc.namespace,
            priority=job.priority if job else 50,
            type=job.type if job else JOB_TYPE_SERVICE,
            triggered_by="alloc-stop",
            job_id=alloc.job_id,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
        self.raft_apply(
            "alloc_update_desired_transition",
            ({alloc_id: DesiredTransition(migrate=True)}, [ev]),
        )
        return ev.id

    def job_scale(self, namespace: str, job_id: str, group: str,
                  count: int, message: str = "") -> str:
        """Scale one task group (reference job_endpoint.go Scale :979:
        count change re-registers the job, bumping its version and
        producing an eval). Returns the eval id."""
        if count < 0:
            raise ValueError("count must be >= 0")
        job = self.state.job_by_id(namespace, job_id)
        if job is None:
            raise KeyError(f"job {job_id} not found")
        job = job.copy()
        tg = job.lookup_task_group(group)
        if tg is None:
            raise ValueError(
                f"task group {group!r} does not exist in job {job_id}"
            )
        if tg.scaling is not None and tg.scaling.enabled:
            lo, hi = tg.scaling.min, tg.scaling.max
            if count < lo or (hi and count > hi):
                raise ValueError(
                    f"count {count} outside scaling bounds [{lo}, {hi}] "
                    f"for group {group!r}"
                )
        prev = tg.count
        tg.count = count
        eval_id = self.job_register(job)
        self.raft_apply(
            "job_scaling_event",
            {
                "namespace": namespace,
                "job_id": job_id,
                "group": group,
                "event": {
                    "Time": now_ns(),
                    "Count": count,
                    "PreviousCount": prev,
                    "Message": message or "submitted via scale API",
                    "EvalID": eval_id,
                },
            },
        )
        return eval_id

    def job_force_evaluate(self, namespace: str, job_id: str) -> str:
        """Create a new eval for the job (reference job_endpoint.go
        Evaluate / `nomad job eval`). Returns the eval id."""
        self.check_eval_admission(namespace)
        job = self.state.job_by_id(namespace, job_id)
        if job is None:
            raise KeyError(f"job {job_id} not found")
        ev = Evaluation(
            id=generate_uuid(),
            namespace=namespace,
            priority=job.priority,
            type=job.type,
            triggered_by="job-eval",
            job_id=job_id,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
        self.raft_apply("eval_update", [ev])
        return ev.id

    def reconcile_job_summaries(self) -> int:
        """Rebuild every job summary from the alloc table (reference
        system_endpoint.go ReconcileJobSummaries / `system reconcile
        summaries`). Returns how many jobs were recomputed (raft_apply
        returns the LOG INDEX, not the FSM result — count from state)."""
        n = len(self.state.jobs())
        self.raft_apply("summaries_reconcile", None)
        return n

    def job_plan(self, job: Job, diff: bool = True) -> dict:
        """Dry-run the candidate job: run the real scheduler against a
        snapshot without committing; return annotations + diff + failures
        (reference job_endpoint.go:521 + scheduler/annotate.go)."""
        from .job_plan import plan_job

        job = job.copy()
        # same admission mutations register applies — or the plan would
        # diff a memory_max the register is about to strip and show the
        # injected connect sidecars as deletions
        job.canonicalize()
        from ..connect import inject_connect_sidecars

        inject_connect_sidecars(job)
        self.apply_memory_oversubscription_gate(job)
        return plan_job(self.state, job, diff, self.scheduler_config)

    def job_deregister(self, namespace: str, job_id: str, purge: bool = False) -> str:
        job = self.state.job_by_id(namespace, job_id)
        ev = Evaluation(
            id=generate_uuid(),
            namespace=namespace,
            priority=job.priority if job else 50,
            type=job.type if job else JOB_TYPE_SERVICE,
            triggered_by=EVAL_TRIGGER_JOB_DEREGISTER,
            job_id=job_id,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
        self.raft_apply("job_deregister", (namespace, job_id, purge, ev))
        self.blocked_evals.untrack(namespace, job_id)
        return ev.id

    # -- node endpoint -------------------------------------------------

    def node_register(self, node) -> float:
        """Returns the heartbeat TTL (reference node_endpoint.go Register)."""
        node = node.copy()
        if not node.status:
            node.status = NODE_STATUS_READY
        prev = self.state.node_by_id(node.id)
        was_ready = prev is not None and prev.ready()
        # Registration storms share node_register_batch raft entries;
        # the direct path serves followers applying forwarded writes and
        # anything running before leadership is established.
        if not self.register_batcher.submit(node):
            self.raft_apply("node_register", node)
        # A node that BECAME ready may unblock system jobs / blocked
        # evals (reference node_endpoint.go Register -> createNodeEvals).
        # A re-registration that didn't change readiness mints no evals:
        # a 10k-node reconnect storm must not multiply eval_update raft
        # entries for placements that already exist.
        stored = self.state.node_by_id(node.id)
        if stored is not None and stored.ready() and not was_ready:
            self._create_node_evals(node.id)
        return self.heartbeaters.reset(node.id)

    def node_heartbeat(self, node_id: str) -> float:
        """Node.UpdateStatus(ready) fast-path: rearm the TTL."""
        node = self.state.node_by_id(node_id)
        if node is None:
            raise KeyError(f"unknown node {node_id}")
        if node.status != NODE_STATUS_READY:
            self.node_update_status(node_id, NODE_STATUS_READY)
        return self.heartbeaters.reset(node_id)

    def node_update_status(self, node_id: str, status: str) -> None:
        prev = self.state.node_by_id(node_id)
        prev_status = prev.status if prev is not None else ""
        self.raft_apply("node_update_status", (node_id, status))
        if status == NODE_STATUS_DOWN:
            self.heartbeaters.clear(node_id)
            self._create_node_evals(node_id)
        elif status == NODE_STATUS_READY and prev_status != NODE_STATUS_READY:
            # A recovered node (down -> ready via heartbeat) needs its
            # system jobs re-placed and class-blocked evals re-run —
            # re-registration preserves the stored status, so this
            # transition is where the evals must come from (reference
            # node_endpoint.go UpdateStatus -> createNodeEvals).
            self._create_node_evals(node_id)
            node = self.state.node_by_id(node_id)
            if node is not None:
                self.blocked_evals.unblock(
                    node.computed_class, self.state.latest_index()
                )

    def node_update_drain(
        self, node_id: str, drain: Optional[DrainStrategy], mark_eligible: bool = False
    ) -> None:
        self.raft_apply("node_update_drain", (node_id, drain, mark_eligible))
        if drain is not None:
            self._create_node_evals(node_id, trigger=EVAL_TRIGGER_NODE_DRAIN)

    def node_update_eligibility(self, node_id: str, eligibility: str) -> None:
        self.raft_apply("node_update_eligibility", (node_id, eligibility))

    def _invalidate_heartbeat(self, node_id: str) -> None:
        """TTL expired: node is presumed dead (reference heartbeat.go:128)."""
        logger.warning("node %s missed heartbeat; marking down", node_id)
        # churn observability: spot-node loss rate and the spot-churn
        # scenario's "no alloc stranded past the TTL" evidence
        metrics.incr("nomad.heartbeat.expired")
        try:
            self.node_update_status(node_id, NODE_STATUS_DOWN)
        except KeyError:
            pass
        except Exception:
            # A deposed or quorumless leader cannot commit the down-mark
            # (NotLeaderError / commit timeout during a partition); the
            # next real leader's timers re-derive liveness — don't let
            # the raft error escape into the Timer thread.
            logger.exception("node %s down-mark failed", node_id)

    def _invalidate_heartbeat_batch(self, node_ids: list[str]) -> None:
        """A wheel sweep's whole expiry crop, committed as ONE
        node_batch_update_status raft entry plus ONE eval_update — a
        mass expiry (partition, leader stall) costs a bounded number of
        log entries instead of two per node."""
        known = [
            nid for nid in node_ids if self.state.node_by_id(nid) is not None
        ]
        if not known:
            return
        metrics.incr("nomad.heartbeat.expired", len(known))
        metrics.incr("nomad.heartbeat.expire_batches")
        blackbox.record(
            blackbox.KIND_EXPIRY, "heartbeat_wheel", expired=len(known),
            rel=[f"node:{nid}" for nid in known[:16]],
        )
        logger.warning(
            "%d node(s) missed heartbeats; marking down in one batch",
            len(known),
        )
        try:
            self.raft_apply(
                "node_batch_update_status", (known, NODE_STATUS_DOWN)
            )
        except KeyError:
            return
        except Exception:
            # same discipline as the single-node path: a deposed or
            # quorumless leader drops the down-mark; the next leader's
            # wheel re-derives liveness
            logger.exception(
                "batched down-mark failed for %d node(s)", len(known)
            )
            return
        metrics.incr("nomad.fleet.node_raft_batches")
        metrics.incr("nomad.fleet.node_raft_coalesced", len(known))
        evals: list[Evaluation] = []
        for nid in known:
            self.heartbeaters.clear(nid)
            evals.extend(self._build_node_evals(nid))
        if evals:
            try:
                self.raft_apply("eval_update", evals)
            except Exception:
                logger.exception(
                    "eval_update for batched expiry failed (%d evals)",
                    len(evals),
                )

    def _create_node_evals(
        self, node_id: str, trigger: str = EVAL_TRIGGER_NODE_UPDATE
    ) -> list[str]:
        evals = self._build_node_evals(node_id, trigger)
        if evals:
            self.raft_apply("eval_update", evals)
        return [e.id for e in evals]

    def _build_node_evals(
        self, node_id: str, trigger: str = EVAL_TRIGGER_NODE_UPDATE
    ) -> list[Evaluation]:
        """One eval per job with allocs on the node (reference
        node_endpoint.go:495 createNodeEvals). Build-only so batch
        callers can merge many nodes' evals into one raft entry."""
        node = self.state.node_by_id(node_id)
        evals: list[Evaluation] = []
        seen: set[tuple[str, str]] = set()
        for alloc in self.state.allocs_by_node(node_id):
            key = (alloc.namespace, alloc.job_id)
            if key in seen or alloc.terminal_status():
                continue
            seen.add(key)
            job = alloc.job or self.state.job_by_id(*key)
            if job is None:
                continue
            evals.append(
                Evaluation(
                    id=generate_uuid(),
                    namespace=alloc.namespace,
                    priority=job.priority,
                    type=job.type,
                    triggered_by=trigger,
                    job_id=alloc.job_id,
                    node_id=node_id,
                    node_modify_index=node.modify_index if node else 0,
                    status=EVAL_STATUS_PENDING,
                    create_time=now_ns(),
                    modify_time=now_ns(),
                )
            )
        # system jobs must also react to NEW nodes with no allocs yet
        if trigger == EVAL_TRIGGER_NODE_UPDATE and node is not None and node.ready():
            for job in self.state.jobs():
                if job.type in ("system", "sysbatch") and (job.namespace, job.id) not in seen:
                    evals.append(
                        Evaluation(
                            id=generate_uuid(),
                            namespace=job.namespace,
                            priority=job.priority,
                            type=job.type,
                            triggered_by=trigger,
                            job_id=job.id,
                            node_id=node_id,
                            status=EVAL_STATUS_PENDING,
                            create_time=now_ns(),
                            modify_time=now_ns(),
                        )
                    )
        return evals

    # -- deployment endpoint (reference nomad/deployment_endpoint.go) --

    def deployment_promote(
        self, deployment_id: str, groups: Optional[list[str]] = None
    ) -> None:
        d = self.state.deployment_by_id(deployment_id)
        if d is None:
            raise KeyError(f"unknown deployment {deployment_id}")
        if not d.active():
            raise ValueError(f"deployment {deployment_id} is terminal")
        self.deployment_watcher.promote(d, groups)

    def deployment_pause(self, deployment_id: str, pause: bool) -> None:
        d = self.state.deployment_by_id(deployment_id)
        if d is None:
            raise KeyError(f"unknown deployment {deployment_id}")
        if not d.active():
            raise ValueError(f"deployment {deployment_id} is terminal")
        self.deployment_watcher.pause(d, pause)

    def deployment_fail(self, deployment_id: str) -> None:
        d = self.state.deployment_by_id(deployment_id)
        if d is None:
            raise KeyError(f"unknown deployment {deployment_id}")
        if not d.active():
            raise ValueError(f"deployment {deployment_id} is terminal")
        self.deployment_watcher.fail_deployment(d)

    def alloc_set_health(
        self, deployment_id: str, healthy: list[str], unhealthy: list[str]
    ) -> None:
        """Deployment.SetAllocHealth (manual health override)."""
        d = self.state.deployment_by_id(deployment_id)
        if d is None:
            raise KeyError(f"unknown deployment {deployment_id}")
        self.raft_apply(
            "deployment_alloc_health",
            {
                "deployment_id": deployment_id,
                "healthy_ids": healthy,
                "unhealthy_ids": unhealthy,
            },
        )

    # -- job revert / dispatch (reference nomad/job_endpoint.go) -------

    def job_revert(self, namespace: str, job_id: str, version: int) -> str:
        """Re-register an older job version (reference Job.Revert)."""
        current = self.state.job_by_id(namespace, job_id)
        if current is None:
            raise KeyError(f"unknown job {job_id}")
        if version == current.version:
            raise ValueError(f"job is already at version {version}")
        target = self.state.job_version(namespace, job_id, version)
        if target is None:
            raise KeyError(f"job {job_id} has no version {version}")
        revert = target.copy()
        revert.stable = False
        return self.job_register(revert)

    def job_dispatch(
        self,
        namespace: str,
        job_id: str,
        payload: bytes = b"",
        meta: Optional[dict[str, str]] = None,
    ) -> tuple[str, str]:
        """Dispatch a parameterized job (reference Job.Dispatch). Returns
        (child_job_id, eval_id)."""
        self.check_eval_admission(namespace)
        parent = self.state.job_by_id(namespace, job_id)
        if parent is None:
            raise KeyError(f"unknown job {job_id}")
        if not parent.is_parameterized():
            raise ValueError(f"job {job_id} is not parameterized")
        cfg = parent.parameterized
        meta = dict(meta or {})
        if cfg.payload == "required" and not payload:
            raise ValueError("payload is required by this job")
        if cfg.payload == "forbidden" and payload:
            raise ValueError("payload is forbidden by this job")
        for key in cfg.meta_required:
            if key not in meta:
                raise ValueError(f"missing required dispatch meta {key!r}")
        for key in meta:
            if key not in cfg.meta_required and key not in cfg.meta_optional:
                raise ValueError(f"dispatch meta {key!r} not allowed")
        child = parent.copy()
        child.id = f"{parent.id}/dispatch-{now_ns() // 1_000_000_000}-{generate_uuid()[:8]}"
        child.name = child.id
        child.parent_id = parent.id
        child.dispatched = True
        child.payload = payload
        child.meta.update(meta)
        child.status = ""
        ev = Evaluation(
            id=generate_uuid(),
            namespace=child.namespace,
            priority=child.priority,
            type=child.type,
            triggered_by="job-register",
            job_id=child.id,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
        self.raft_apply("job_register", (child, ev))
        return child.id, ev.id

    # -- GC (reference nomad/system_endpoint.go + leader.go) -----------

    # -- ACL endpoint (reference nomad/acl_endpoint.go) -----------------

    def acl_bootstrap(self):
        """One-shot initial management token (reference ACL.Bootstrap).
        The lock closes the check-then-act window between two concurrent
        bootstrap requests (the reference uses a bootstrap-index CAS)."""
        from ..acl.structs import ACLToken

        with self._acl_bootstrap_lock:
            if self.state.acl_has_management_token():
                raise ConflictError("ACL bootstrap already done")
            token = ACLToken.new(name="Bootstrap Token", type="management")
            self.raft_apply("acl_token_upsert", [token])
            return self.state.acl_token_by_accessor(token.accessor_id)

    def acl_policy_upsert(self, policies) -> None:
        for pol in policies:
            pol.validate()
        self.raft_apply("acl_policy_upsert", policies)

    def acl_policy_delete(self, names: list[str]) -> None:
        self.raft_apply("acl_policy_delete", names)

    def acl_token_create(self, token):
        from ..acl.structs import ACLToken

        if not token.accessor_id:
            fresh = ACLToken.new(
                name=token.name, type=token.type, policies=token.policies
            )
            fresh.global_ = token.global_
            token = fresh
        token.validate()
        self.raft_apply("acl_token_upsert", [token])
        return self.state.acl_token_by_accessor(token.accessor_id)

    def acl_token_delete(self, accessor_ids: list[str]) -> None:
        self.raft_apply("acl_token_delete", accessor_ids)

    def resolve_token(self, secret_id: str):
        """secret → compiled ACL (reference nomad/acl.go ResolveToken).
        None ⇒ anonymous. Cached per (secret, acl table index)."""
        from ..acl import compile_policies, parse_policy
        from ..acl.acl import MANAGEMENT_ACL
        from ..state.store import TABLE_ACL_POLICIES, TABLE_ACL_TOKENS

        if not secret_id:
            return None
        idx = self.state.table_index(TABLE_ACL_POLICIES, TABLE_ACL_TOKENS)
        cached = self._acl_cache.get(secret_id)
        if cached is not None and cached[0] == idx:
            # Expiry is wall-clock, not table-index: check it from the
            # cached entry so hits stay O(1) (the by-secret lookup scans
            # the token table) without letting a compile outlive its TTL.
            exp = cached[2]
            if exp and exp < now_ns():
                raise PermissionError("token expired")
            return cached[1]
        token = self.state.acl_token_by_secret(secret_id)
        if token is None:
            raise PermissionError("token not found")
        if token.expiration_time_ns and token.expiration_time_ns < now_ns():
            raise PermissionError("token expired")
        if token.is_management():
            acl = MANAGEMENT_ACL
        else:
            policies = []
            for name in token.policies:
                pol = self.state.acl_policy_by_name(name)
                if pol is not None:
                    policies.append(parse_policy(pol.rules))
            acl = compile_policies(policies)
        if len(self._acl_cache) > 512:
            self._acl_cache.clear()
        self._acl_cache[secret_id] = (idx, acl, token.expiration_time_ns)
        return acl

    def force_gc(self) -> None:
        """System.GarbageCollect: enqueue a force-gc core eval."""
        self.eval_broker.enqueue(core_eval("force-gc"))

    def _gc_loop(self, stop: threading.Event) -> None:
        """Periodic threshold GC (reference leader.go schedulePeriodic)."""
        while not stop.wait(self.gc_interval_s):
            for kind in (
                "eval-gc", "job-gc", "node-gc", "deployment-gc",
                "service-gc", "token-gc",
            ):
                self.eval_broker.enqueue(core_eval(kind))

    # -- client alloc updates -----------------------------------------

    def update_allocs_from_client(self, allocs: list[Allocation]) -> None:
        """Node.UpdateAlloc: merge client status; failed allocs trigger
        reschedule evals (reference node_endpoint.go UpdateAlloc)."""
        self.raft_apply("alloc_client_update", allocs)
        evals: list[Evaluation] = []
        seen: set[tuple[str, str]] = set()
        for alloc in allocs:
            if alloc.client_status != ALLOC_CLIENT_STATUS_FAILED:
                continue
            key = (alloc.namespace, alloc.job_id)
            if key in seen:
                continue
            stored = self.state.alloc_by_id(alloc.id)
            job = (stored.job if stored else None) or self.state.job_by_id(*key)
            if job is None or job.stopped():
                continue
            seen.add(key)
            evals.append(
                Evaluation(
                    id=generate_uuid(),
                    namespace=alloc.namespace,
                    priority=job.priority,
                    type=job.type,
                    triggered_by=EVAL_TRIGGER_RETRY_FAILED_ALLOC,
                    job_id=alloc.job_id,
                    status=EVAL_STATUS_PENDING,
                    create_time=now_ns(),
                    modify_time=now_ns(),
                )
            )
        if evals:
            self.raft_apply("eval_update", evals)

    # -- client pull (blocking query) ---------------------------------

    def get_client_allocs(
        self, node_id: str, min_index: int = 0, timeout_s: float = 5.0
    ) -> tuple[list[Allocation], int]:
        """Node.GetClientAllocs: blocking query on the node's allocs.

        The seed implementation parked every watcher on the alloc
        TABLE's condition — each plan apply woke all of them
        (``notify_all``) and each re-scanned its node's allocs. The
        watch hub wakes only the nodes a write actually touched; a
        timeout still falls through to a fetch, so the returned
        (allocs, index) contract is unchanged."""
        from ..state.store import TABLE_ALLOCS

        if min_index > 0:
            self.watch_hub.wait_for_node(node_id, min_index, timeout_s)
        index = self.state.wait_for_index([TABLE_ALLOCS], 0, 0.0)
        return self.state.allocs_by_node(node_id), index

    # -- draining helpers ---------------------------------------------

    def wait_for_evals(self, timeout_s: float = 10.0) -> bool:
        """Test helper: block until no ready/in-flight evals remain."""
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if (
                self.eval_broker.ready_count() == 0
                and self.eval_broker.inflight_count() == 0
                and self.plan_queue.depth() == 0
            ):
                return True
            time.sleep(0.01)
        return False
