"""Blackbox wiring: pumps the live control plane into the flight
recorder and turns trigger firings into on-disk incident captures.

The leaf (nomad_tpu/blackbox.py) is pure bookkeeping — journal ring,
trigger math, incident index, timeline merge — and never imports
metrics/trace/stream. This module is the impure half, one instance per
ClusterServer:

  * event pump — a broker subscription over ALL topics journals every
    node/eval/alloc/deployment event with extracted cross-object links
    (``rel: ["eval:<id>", "node:<id>", ...]``), which is what makes the
    timeline reconstructor's causal expansion work;
  * health/trigger loop — journals a periodic health frame (raft
    indices, broker depths, plan-queue depth) and evaluates the trigger
    engine over journal-kind counts + registry counters + last-window
    histogram p99s;
  * incident capture — a firing writes a full debug-bundle-equivalent
    (journal, metrics, traces, profile summary + collapsed stacks,
    solver status, cluster health) under
    ``incident_dir/<ts>-<rule>/``. Capture is single-flight behind a
    non-blocking lock + busy-until deadline (the pprof 429 pattern in
    agent/http.py): a flapping trigger suppresses concurrent writes
    instead of stacking them.

Leadership edges, dup-mint trims, sheds, expiry batches, and
pool-member faults are journaled directly at their hook sites (they
carry context the event stream doesn't); this module only owns the
pumps and the capture.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

from .. import blackbox, metrics
from ..stream.event_broker import SubscriptionClosedError

logger = logging.getLogger("nomad_tpu.server")

DEFAULT_INTERVAL_S = 2.0
CAPTURE_HOLD_S = 5.0

# broker topic -> timeline token kind
_TOPIC_KIND = {
    "Node": "node",
    "Evaluation": "eval",
    "Allocation": "alloc",
    "Job": "job",
    "Deployment": "deployment",
}
# payload attribute -> timeline token kind (cross-object links)
_REL_ATTRS = (
    ("eval_id", "eval"),
    ("node_id", "node"),
    ("job_id", "job"),
    ("deployment_id", "deployment"),
)


def event_rels(topic: str, key: str, payload) -> list[str]:
    """The ``kind:id`` tokens one broker event mentions: the event's
    own object plus every cross-object id its payload carries."""
    rels = []
    kind = _TOPIC_KIND.get(topic)
    if kind and key:
        rels.append(f"{kind}:{key}")
    for attr, k in _REL_ATTRS:
        v = getattr(payload, attr, None)
        if v and isinstance(v, str):
            tok = f"{k}:{v}"
            if tok not in rels:
                rels.append(tok)
    return rels


class BlackboxWiring:
    """Per-ClusterServer pumps + capture for the process-global flight
    recorder. ``interval_s`` is instance-tunable (the heartbeat-wheel
    idiom) so chaos scenarios tighten the trigger loop to fit a test
    budget without faking the evaluation path."""

    def __init__(
        self,
        cluster,
        incident_dir: str = "",
        incident_max: int = blackbox.DEFAULT_INCIDENT_MAX,
        enabled: bool = True,
        interval_s: float = DEFAULT_INTERVAL_S,
    ) -> None:
        self.cluster = cluster
        self.incident_dir = incident_dir or ""
        self.enabled = bool(enabled)
        self.interval_s = float(interval_s)
        self._stop: Optional[threading.Event] = None
        self._threads: list[threading.Thread] = []
        self._provider = None
        # single-flight capture gate (the pprof pattern: non-blocking
        # acquire + busy-until deadline; concurrent firings are
        # suppressed, counted, and report Retry-After upstream)
        self._capture_lock = threading.Lock()
        self._busy_until = 0.0
        if blackbox.recorder().incident_max != int(incident_max):
            blackbox.recorder().set_incident_max(incident_max)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if not self.enabled or self._stop is not None:
            return
        self._stop = threading.Event()
        self._provider = metrics.register_provider(
            "nomad.blackbox", blackbox.recorder().stats
        )
        for name, fn in (
            ("blackbox-pump", self._pump_loop),
            ("blackbox-triggers", self._trigger_loop),
        ):
            t = threading.Thread(
                target=fn, args=(self._stop,), name=name, daemon=True
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        stop, self._stop = self._stop, None
        if stop is None:
            return
        stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []
        if self._provider is not None:
            metrics.unregister_provider("nomad.blackbox", self._provider)
            self._provider = None

    def reload(
        self,
        enabled: Optional[bool] = None,
        incident_dir: Optional[str] = None,
        incident_max: Optional[int] = None,
    ) -> None:
        """SIGHUP path: flip the recording gate / retarget the incident
        dir / resize the ledger without restarting the agent."""
        if incident_dir is not None:
            self.incident_dir = incident_dir
        if incident_max is not None:
            blackbox.recorder().set_incident_max(incident_max)
        if enabled is not None and bool(enabled) != self.enabled:
            self.enabled = bool(enabled)
            # the module flag gates the hook-site record() calls too —
            # process-wide, which matches one-agent-per-process prod
            blackbox.set_enabled(self.enabled)
            if self.enabled:
                self.start()
            else:
                self.stop()

    # -- event pump ----------------------------------------------------

    def _pump_loop(self, stop: threading.Event) -> None:
        broker = self.cluster.server.event_broker
        sub = broker.subscribe(None)
        while not stop.is_set():
            try:
                events = sub.next(timeout_s=0.5)
            except SubscriptionClosedError:
                # evicted (slow consumer) or broker restarted: the gap
                # is counted by nomad.stream.evicted_total; resubscribe
                # from the live head
                try:
                    sub = broker.subscribe(None)
                except Exception:
                    if stop.wait(0.5):
                        return
                continue
            for ev in events:
                rels = event_rels(ev.topic, ev.key, ev.payload)
                blackbox.record(
                    blackbox.KIND_EVENT,
                    rels[0] if rels else ev.key,
                    topic=ev.topic,
                    type=ev.type,
                    index=ev.index,
                    rel=rels,
                )
        try:
            sub.close()
        except Exception:
            pass

    # -- health frames + trigger evaluation ----------------------------

    def _trigger_loop(self, stop: threading.Event) -> None:
        while not stop.wait(self.interval_s):
            try:
                self._health_frame()
                for firing in blackbox.recorder().triggers.evaluate(
                    self._trigger_values()
                ):
                    # the firing's own "kind" (delta|level) would shadow
                    # the journal-row kind positional: journal it as
                    # rule_kind
                    detail = {
                        k: v for k, v in firing.items()
                        if k not in ("rule", "kind")
                    }
                    detail["rule_kind"] = firing["kind"]
                    blackbox.record(
                        blackbox.KIND_TRIGGER, firing["rule"], **detail
                    )
                    self.capture(firing["rule"], firing)
            except Exception:
                logger.exception("blackbox trigger sweep failed")

    def _health_frame(self) -> None:
        c = self.cluster
        raft = c.raft
        srv = c.server
        blackbox.record(
            blackbox.KIND_HEALTH,
            f"node:{c.node_id}",
            raft_state=raft.state,
            term=raft.current_term,
            commit_index=raft.commit_index,
            applied_index=raft.last_applied,
            broker=srv.eval_broker.stats_snapshot(),
            plan_queue_depth=srv.plan_queue.depth(),
            stream=srv.event_broker.stats(),
        )

    def _trigger_values(self) -> dict:
        vals: dict[str, float] = {}
        for kind, n in blackbox.recorder().kind_counts().items():
            vals[f"journal:{kind}"] = float(n)
        snap = metrics.snapshot()
        for name, v in snap["counters"].items():
            vals[f"counter:{name}"] = float(v)
        for name, s in snap["samples"].items():
            w = s.get("window") or s
            p99 = w.get("p99")
            if p99 is not None:
                vals[f"p99:{name}"] = float(p99)
        return vals

    # -- incident capture ----------------------------------------------

    def capture(self, rule: str, detail: dict) -> Optional[dict]:
        """Write one incident bundle; single-flight. Returns the ledger
        record, or None when suppressed by the in-progress gate."""
        if not self._capture_lock.acquire(blocking=False):
            blackbox.recorder().suppress_incident()
            return None
        try:
            # FIRST thing under the lock (the pprof discipline): a
            # crashed capture must not leave busy_until stale-low
            self._busy_until = time.monotonic() + CAPTURE_HOLD_S
            t0 = time.monotonic()
            incident_id = "%s-%s" % (
                time.strftime("%Y%m%d-%H%M%S"), rule
            )
            path = ""
            if self.incident_dir:
                path = os.path.join(self.incident_dir, incident_id)
                try:
                    self._write_bundle(path, rule, detail)
                except Exception:
                    logger.exception(
                        "blackbox incident write failed: %s", path
                    )
                    path = ""
            rec = blackbox.recorder().add_incident(
                incident_id, detail.get("reason") or rule, path, detail
            )
            metrics.observe(
                "nomad.blackbox.capture_seconds",
                time.monotonic() - t0,
            )
            logger.warning(
                "blackbox incident captured: %s (%s)",
                incident_id, detail.get("reason") or rule,
            )
            return rec
        finally:
            self._capture_lock.release()

    def retry_after_s(self) -> float:
        """How long a single-flight-suppressed caller should wait."""
        return max(0.1, self._busy_until - time.monotonic())

    def _write_bundle(self, path: str, rule: str, detail: dict) -> None:
        from .. import hostobs, solverobs, trace

        os.makedirs(path, exist_ok=True)

        def dump(name: str, fn) -> None:
            try:
                payload = fn()
            except Exception as e:  # capture what we can, note the rest
                payload = {"error": str(e)}
            with open(os.path.join(path, name), "w") as f:
                if isinstance(payload, str):
                    f.write(payload)
                else:
                    json.dump(payload, f, indent=1, default=str)

        dump("meta.json", lambda: {
            "rule": rule,
            "detail": detail,
            "node": self.cluster.node_id,
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        })
        dump("journal.json", lambda: blackbox.recorder().snapshot())
        dump("metrics.json", metrics.snapshot)
        dump("traces.json", lambda: trace.recorder().list(limit=200))
        dump("profile_status.json", lambda: hostobs.snapshot(top=50))
        dump("profile_stacks.txt", hostobs.collapsed)
        dump("solver_status.json", solverobs.snapshot)
        dump("cluster_health.json", lambda: self.cluster.cluster_health(
            per_peer_timeout_s=0.5, top=5
        ))
