"""Leader-only node drainer.

Reference: nomad/drainer/ — drainer.go (RaftApplier :45), watch_nodes.go
(tracks draining nodes), watch_jobs.go (per-job migrate-stanza rate
limiting), drain_heap.go (deadline timers).

Redesign: one batched `run_once` pass over a single snapshot computes, for
every draining node at once, which allocs to mark `desired_transition.
migrate` — bounded per job by the migrate stanza's max_parallel — plus
which nodes are done draining. A poll thread drives it; tests call
`run_once` directly.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..structs import Evaluation, generate_uuid, now_ns
from ..structs.structs import (
    ALLOC_CLIENT_STATUS_RUNNING,
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_NODE_DRAIN,
    JOB_TYPE_BATCH,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSBATCH,
    JOB_TYPE_SYSTEM,
    DesiredTransition,
)

logger = logging.getLogger("nomad_tpu.drainer")


class NodeDrainer:
    def __init__(self, state, raft_apply, poll_interval_s: float = 0.25) -> None:
        self.state = state
        self.raft_apply = raft_apply
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        # Fresh Event per incarnation: a thread that outlives a
        # join(timeout) keeps polling ITS event (passed as arg) and still
        # exits, instead of seeing a cleared shared flag and resuming.
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(self._stop,), daemon=True, name="node-drainer"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self, stop: threading.Event) -> None:
        while not stop.wait(self.poll_interval_s):
            try:
                self.run_once()
            except Exception:
                logger.exception("drainer pass failed")

    # -- the batched drain pass ----------------------------------------

    def run_once(self) -> int:
        """Returns the number of allocs newly marked for migration."""
        draining = [n for n in self.state.nodes() if n.drain]
        if not draining:
            return 0

        transitions: dict[str, DesiredTransition] = {}
        eval_jobs: set[tuple[str, str]] = set()
        done_nodes: dict[str, None] = {}

        # Candidate allocs to mark, grouped per task group across ALL
        # draining nodes; the migrate budget is per task group, not per
        # node (reference watch_jobs.go handleTaskGroup).
        candidates: dict[tuple[str, str, str], list] = {}
        jobs: dict[tuple[str, str, str], object] = {}

        for node in draining:
            strategy = node.drain_strategy
            force = strategy.deadline_expired()
            remaining = []
            for a in self.state.allocs_by_node(node.id):
                if a.terminal_status():
                    continue
                # Prefer the CURRENT job from state: a live migrate-stanza
                # change (e.g. raising max_parallel mid-drain) must take
                # effect; the alloc's embedded job is placement-time stale.
                job = self.state.job_by_id(a.namespace, a.job_id) or a.job
                system = job is not None and job.type in (
                    JOB_TYPE_SYSTEM,
                    JOB_TYPE_SYSBATCH,
                )
                if system and strategy.ignore_system_jobs:
                    continue
                remaining.append((a, job, system))

            if not remaining:
                done_nodes[node.id] = None
                continue
            service_left = [r for r in remaining if not r[2]]

            for a, job, system in remaining:
                if a.desired_transition.should_migrate():
                    continue  # already marked
                if system and service_left and not force:
                    # System allocs are only stopped once every service
                    # alloc has drained (reference drainer.go: system
                    # drains last) or at the deadline.
                    continue
                if force:
                    transitions[a.id] = DesiredTransition(migrate=True)
                    eval_jobs.add((a.namespace, a.job_id))
                    continue
                if job is not None and job.type == JOB_TYPE_BATCH:
                    # Batch allocs are never migrated by the rate-limited
                    # path — they run to completion (or the deadline);
                    # the node stays draining meanwhile (reference
                    # watch_jobs.go: "We don't mark batch for drain").
                    continue
                key = (a.namespace, a.job_id, a.task_group)
                candidates.setdefault(key, []).append(a)
                jobs[key] = job

        # Rate-limited marking: an alloc already drained off a draining
        # node keeps holding a max_parallel slot until its REPLACEMENT
        # reports health — expressed as the reference does it: allowed new
        # marks = healthy-anywhere − (group count − max_parallel)
        # (reference watch_jobs.go handleTaskGroup thresholdCount;
        # "healthy" there is IsHealthy — healthy==true — on any
        # non-terminal alloc; allocs without a deployment fall back to
        # client running status).
        for key, allocs in candidates.items():
            ns, job_id, tg_name = key
            job = jobs[key]
            limit = self._max_parallel(job, tg_name)
            count = self._group_count(job, tg_name)
            healthy = 0
            for a in self.state.allocs_by_job(ns, job_id):
                if a.terminal_status() or a.task_group != tg_name:
                    continue
                if a.desired_transition.should_migrate():
                    # Marked but not yet stopped by the scheduler: it is
                    # mid-migration and holds its slot (the reference sees
                    # these as terminal by the time its watcher re-fires).
                    continue
                ds = a.deployment_status
                if (ds is not None and ds.healthy is True) or (
                    ds is None and a.client_status == ALLOC_CLIENT_STATUS_RUNNING
                ):
                    healthy += 1
            allowed = healthy - (count - limit)
            for a in allocs[: max(0, allowed)]:
                transitions[a.id] = DesiredTransition(migrate=True)
                eval_jobs.add((ns, job_id))

        if transitions or done_nodes:
            evals = [
                self._drain_eval(ns, job_id) for ns, job_id in sorted(eval_jobs)
            ]
            if transitions:
                self.raft_apply(
                    "alloc_update_desired_transition", (transitions, evals)
                )
            if done_nodes:
                # Drain complete: drop the strategy, node stays ineligible
                # (reference watch_nodes.go Remove + batcher).
                self.raft_apply(
                    "batch_node_drain_update",
                    {node_id: None for node_id in done_nodes},
                )
        return len(transitions)

    def _max_parallel(self, job, group: str) -> int:
        if job is None:
            return 1
        tg = job.lookup_task_group(group)
        if tg is None or tg.migrate is None:
            return 1
        return max(1, tg.migrate.max_parallel)

    def _group_count(self, job, group: str) -> int:
        if job is None:
            return 1
        tg = job.lookup_task_group(group)
        return tg.count if tg is not None else 1

    def _drain_eval(self, namespace: str, job_id: str) -> Evaluation:
        job = self.state.job_by_id(namespace, job_id)
        return Evaluation(
            id=generate_uuid(),
            namespace=namespace,
            priority=job.priority if job else 50,
            type=job.type if job else JOB_TYPE_SERVICE,
            triggered_by=EVAL_TRIGGER_NODE_DRAIN,
            job_id=job_id,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
