"""Leader-only node drainer.

Reference: nomad/drainer/ — drainer.go (RaftApplier :45), watch_nodes.go
(tracks draining nodes), watch_jobs.go (per-job migrate-stanza rate
limiting), drain_heap.go (deadline timers).

Redesign: one batched `run_once` pass over a single snapshot computes, for
every draining node at once, which allocs to mark `desired_transition.
migrate` — bounded per job by the migrate stanza's max_parallel — plus
which nodes are done draining. A poll thread drives it; tests call
`run_once` directly.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..structs import Evaluation, generate_uuid, now_ns
from ..structs.structs import (
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_NODE_DRAIN,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSBATCH,
    JOB_TYPE_SYSTEM,
    DesiredTransition,
)

logger = logging.getLogger("nomad_tpu.drainer")


class NodeDrainer:
    def __init__(self, state, raft_apply, poll_interval_s: float = 0.25) -> None:
        self.state = state
        self.raft_apply = raft_apply
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="node-drainer"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.run_once()
            except Exception:
                logger.exception("drainer pass failed")

    # -- the batched drain pass ----------------------------------------

    def run_once(self) -> int:
        """Returns the number of allocs newly marked for migration."""
        draining = [n for n in self.state.nodes() if n.drain]
        if not draining:
            return 0

        transitions: dict[str, DesiredTransition] = {}
        eval_jobs: set[tuple[str, str]] = set()
        done_nodes: dict[str, None] = {}

        # In-flight migrations per job across ALL draining nodes: an alloc
        # already marked migrate whose replacement isn't healthy yet holds a
        # max_parallel slot (reference watch_jobs.go handleTaskGroup).
        inflight: dict[tuple[str, str, str], int] = {}
        for node in draining:
            for a in self.state.allocs_by_node(node.id):
                if a.terminal_status():
                    continue
                if a.desired_transition.should_migrate():
                    key = (a.namespace, a.job_id, a.task_group)
                    inflight[key] = inflight.get(key, 0) + 1

        for node in draining:
            strategy = node.drain_strategy
            force = strategy.deadline_expired()
            remaining = []
            for a in self.state.allocs_by_node(node.id):
                if a.terminal_status():
                    continue
                job = a.job or self.state.job_by_id(a.namespace, a.job_id)
                system = job is not None and job.type in (
                    JOB_TYPE_SYSTEM,
                    JOB_TYPE_SYSBATCH,
                )
                if system and strategy.ignore_system_jobs:
                    continue
                if system:
                    # System allocs are only stopped once every service
                    # alloc has drained (reference drainer.go: system
                    # drains last) or at the deadline.
                    remaining.append((a, job, True))
                else:
                    remaining.append((a, job, False))

            service_left = [r for r in remaining if not r[2]]
            if not remaining:
                done_nodes[node.id] = None
                continue

            for a, job, system in remaining:
                if a.desired_transition.should_migrate():
                    continue  # already marked
                if system and service_left and not force:
                    continue  # system waits for services
                key = (a.namespace, a.job_id, a.task_group)
                if not force:
                    limit = self._max_parallel(job, a.task_group)
                    if inflight.get(key, 0) >= limit:
                        continue
                transitions[a.id] = DesiredTransition(migrate=True)
                inflight[key] = inflight.get(key, 0) + 1
                eval_jobs.add((a.namespace, a.job_id))

        if transitions or done_nodes:
            evals = [
                self._drain_eval(ns, job_id) for ns, job_id in sorted(eval_jobs)
            ]
            if transitions:
                self.raft_apply(
                    "alloc_update_desired_transition", (transitions, evals)
                )
            if done_nodes:
                # Drain complete: drop the strategy, node stays ineligible
                # (reference watch_nodes.go Remove + batcher).
                self.raft_apply(
                    "batch_node_drain_update",
                    {node_id: None for node_id in done_nodes},
                )
        return len(transitions)

    def _max_parallel(self, job, group: str) -> int:
        if job is None:
            return 1
        tg = job.lookup_task_group(group)
        if tg is None or tg.migrate is None:
            return 1
        return max(1, tg.migrate.max_parallel)

    def _drain_eval(self, namespace: str, job_id: str) -> Evaluation:
        job = self.state.job_by_id(namespace, job_id)
        return Evaluation(
            id=generate_uuid(),
            namespace=namespace,
            priority=job.priority if job else 50,
            type=job.type if job else JOB_TYPE_SERVICE,
            triggered_by=EVAL_TRIGGER_NODE_DRAIN,
            job_id=job_id,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
