"""Volume claim lifecycle watcher.

Reference: nomad/volumewatcher/volumes_watcher.go — a leader-only loop
that releases volume claims whose allocations are terminal or gone, so a
single-writer volume freed by a dead alloc becomes claimable again
without operator intervention.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

logger = logging.getLogger("nomad_tpu.server.volumes")


class VolumeWatcher:
    def __init__(self, state, raft_apply, poll_interval_s: float = 1.0) -> None:
        self.state = state
        self.raft_apply = raft_apply
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(self._stop,), daemon=True,
            name="volume-watcher",
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self, stop: threading.Event) -> None:
        while not stop.wait(self.poll_interval_s):
            try:
                self.run_once()
            except Exception:
                logger.exception("volume watcher pass failed")

    def run_once(self) -> None:
        stale: set[str] = set()
        for vol in self.state.volumes():
            for claim in vol.claims.values():
                alloc = self.state.alloc_by_id(claim.alloc_id)
                if alloc is None or alloc.terminal_status():
                    stale.add(claim.alloc_id)
        if stale:
            logger.info("releasing %d stale volume claims", len(stale))
            self.raft_apply("volume_claim_release", sorted(stale))
