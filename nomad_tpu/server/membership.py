"""Gossip membership: SWIM-style failure detection + state merge.

Reference: the Go tree vendors hashicorp/serf + memberlist and wires them
in nomad/server.go:394 (setupSerf) / nomad/serf.go (member-join and
member-failed events feed leader reconciliation, nomad/leader.go:1121
reconcileMember → addRaftPeer/removeRaftPeer).

This is a from-scratch SWIM-lite over the RPC fabric:
  * every `probe_interval_s` each member pings one random peer; the ping
    piggybacks the full member list both ways (anti-entropy merge — small
    control planes don't need memberlist's delta broadcasts);
  * a failed direct probe triggers indirect probes through up to `k`
    other members (SWIM's core trick: distinguish "target died" from
    "my link to target is bad");
  * still unreachable ⇒ suspect; suspicion timeout ⇒ failed, event fired;
  * incarnation numbers let a live member refute stale failure rumors —
    a member seeing itself reported failed bumps its incarnation.

Merge rule: higher incarnation wins; at equal incarnation, alive < suspect
< failed (worse status wins, so rumors propagate).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..rpc import ConnPool

logger = logging.getLogger("nomad_tpu.membership")

ALIVE = "alive"
SUSPECT = "suspect"
FAILED = "failed"
LEFT = "left"

_STATUS_RANK = {ALIVE: 0, SUSPECT: 1, FAILED: 2, LEFT: 3}


@dataclass
class Member:
    id: str
    addr: tuple  # (host, port) of the member's RPC fabric
    status: str = ALIVE
    incarnation: int = 0
    tags: dict = field(default_factory=dict)  # role/region/etc.

    def to_wire(self) -> dict:
        return {
            "id": self.id,
            "addr": list(self.addr),
            "status": self.status,
            "incarnation": self.incarnation,
            "tags": dict(self.tags),
        }

    @staticmethod
    def from_wire(d: dict) -> "Member":
        return Member(
            id=d["id"],
            addr=tuple(d["addr"]),
            status=d["status"],
            incarnation=d["incarnation"],
            tags=dict(d.get("tags", {})),
        )


class SerfEndpoint:
    """RPC surface registered as `Serf` on the fabric."""

    def __init__(self, mgr: "Membership") -> None:
        self._mgr = mgr

    def ping(self, args):
        self._mgr._merge([Member.from_wire(m) for m in args.get("members", [])])
        return {"members": self._mgr.wire_members()}

    def join(self, args):
        self._mgr._merge([Member.from_wire(m) for m in args.get("members", [])])
        return {"members": self._mgr.wire_members()}

    def indirect_ping(self, args):
        """Probe `target` on behalf of a peer whose direct probe failed."""
        target = tuple(args["target"])
        try:
            self._mgr.pool.call(
                target,
                "Serf.ping",
                {"members": self._mgr.wire_members()},
                timeout_s=self._mgr.probe_timeout_s,
            )
            return {"ok": True}
        except Exception:
            return {"ok": False}

    def leave(self, args):
        return self._mgr._on_leave_rumor(args["id"], args["incarnation"])


class Membership:
    def __init__(
        self,
        node_id: str,
        addr: tuple[str, int],
        pool: Optional[ConnPool] = None,
        tags: Optional[dict] = None,
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 1.0,
        suspicion_timeout_s: float = 3.0,
        indirect_k: int = 3,
        on_event: Optional[Callable[[str, Member], None]] = None,
    ) -> None:
        self.node_id = node_id
        self.pool = pool or ConnPool()
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.suspicion_timeout_s = suspicion_timeout_s
        self.indirect_k = indirect_k
        # on_event(kind, member) with kind in
        # member-join / member-failed / member-leave / member-alive
        self.on_event = on_event
        self._lock = threading.Lock()
        self.local = Member(node_id, addr, ALIVE, 0, dict(tags or {}))
        self._members: dict[str, Member] = {node_id: self.local}
        self._suspect_since: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.endpoint = SerfEndpoint(self)

    # -- views ---------------------------------------------------------

    def members(self) -> list[Member]:
        with self._lock:
            return [
                Member(m.id, m.addr, m.status, m.incarnation, dict(m.tags))
                for m in self._members.values()
            ]

    def alive_members(self) -> list[Member]:
        return [m for m in self.members() if m.status == ALIVE]

    def wire_members(self) -> list[dict]:
        with self._lock:
            return [m.to_wire() for m in self._members.values()]

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._probe_loop, name=f"serf-{self.node_id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    def join(self, seeds: list[tuple[str, int]]) -> int:
        """Contact seeds, merge their views. Returns contacted count."""
        n = 0
        for addr in seeds:
            if tuple(addr) == self.local.addr:
                continue
            try:
                resp = self.pool.call(
                    tuple(addr),
                    "Serf.join",
                    {"members": self.wire_members()},
                    timeout_s=self.probe_timeout_s,
                )
                self._merge([Member.from_wire(m) for m in resp["members"]])
                n += 1
            except Exception:
                logger.debug("join seed %s unreachable", addr)
        return n

    def leave(self) -> None:
        """Graceful departure: tell everyone before going away."""
        with self._lock:
            self.local.incarnation += 1
            self.local.status = LEFT
            peers = [
                m for m in self._members.values()
                if m.id != self.node_id and m.status == ALIVE
            ]
        for m in peers:
            try:
                self.pool.call(
                    m.addr,
                    "Serf.leave",
                    {"id": self.node_id, "incarnation": self.local.incarnation},
                    timeout_s=self.probe_timeout_s,
                )
            except Exception:
                pass
        self.stop()

    # -- probe loop ----------------------------------------------------

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            target = self._pick_probe_target()
            if target is not None:
                self._probe(target)
            self._expire_suspects()

    def _pick_probe_target(self) -> Optional[Member]:
        with self._lock:
            candidates = [
                m
                for m in self._members.values()
                if m.id != self.node_id and m.status in (ALIVE, SUSPECT)
            ]
        return random.choice(candidates) if candidates else None

    def _probe(self, target: Member) -> None:
        try:
            resp = self.pool.call(
                target.addr,
                "Serf.ping",
                {"members": self.wire_members()},
                timeout_s=self.probe_timeout_s,
            )
            self._merge([Member.from_wire(m) for m in resp["members"]])
            self._mark_alive(target.id)
            return
        except Exception:
            pass
        # Direct probe failed: ask up to k others to try (SWIM indirect).
        with self._lock:
            helpers = [
                m
                for m in self._members.values()
                if m.id not in (self.node_id, target.id) and m.status == ALIVE
            ]
        for helper in random.sample(helpers, min(self.indirect_k, len(helpers))):
            try:
                resp = self.pool.call(
                    helper.addr,
                    "Serf.indirect_ping",
                    {"target": list(target.addr)},
                    timeout_s=self.probe_timeout_s * 2,
                )
                if resp.get("ok"):
                    self._mark_alive(target.id)
                    return
            except Exception:
                continue
        self._mark_suspect(target.id)

    def _expire_suspects(self) -> None:
        now = time.monotonic()
        newly_failed: list[Member] = []
        with self._lock:
            for mid, since in list(self._suspect_since.items()):
                if now - since >= self.suspicion_timeout_s:
                    m = self._members.get(mid)
                    del self._suspect_since[mid]
                    if m is not None and m.status == SUSPECT:
                        m.status = FAILED
                        newly_failed.append(m)
        for m in newly_failed:
            logger.info("member %s failed", m.id)
            self._fire("member-failed", m)

    # -- state transitions ---------------------------------------------

    def _mark_alive(self, member_id: str) -> None:
        with self._lock:
            m = self._members.get(member_id)
            if m is None or m.status == ALIVE:
                return
            m.status = ALIVE
            self._suspect_since.pop(member_id, None)
        self._fire("member-alive", m)

    def _mark_suspect(self, member_id: str) -> None:
        with self._lock:
            m = self._members.get(member_id)
            if m is None or m.status != ALIVE:
                return
            m.status = SUSPECT
            self._suspect_since[member_id] = time.monotonic()
        logger.debug("member %s suspected", member_id)

    def _on_leave_rumor(self, member_id: str, incarnation: int) -> bool:
        """Returns whether the rumor was ACCEPTED — a caller counting
        acknowledgements (force-leave) must not mistake a dropped
        lower-incarnation rumor for one."""
        with self._lock:
            m = self._members.get(member_id)
            if m is None or incarnation < m.incarnation:
                return False
            m.incarnation = incarnation
            m.status = LEFT
            self._suspect_since.pop(member_id, None)
        self._fire("member-leave", m)
        return True

    def _merge(self, remote: list[Member]) -> None:
        # (kind, member) transitions to fire after releasing the lock —
        # failures learned by RUMOR must fire events too, not only
        # directly-detected ones (the leader reconciles on them).
        fired: list[tuple[str, Member]] = []
        refute = False
        with self._lock:
            for rm in remote:
                if rm.id == self.node_id:
                    # Someone thinks we're suspect/failed: refute by
                    # bumping our incarnation past the rumor's.
                    if rm.status != ALIVE and rm.incarnation >= self.local.incarnation:
                        self.local.incarnation = rm.incarnation + 1
                        refute = True
                    continue
                cur = self._members.get(rm.id)
                if cur is None:
                    self._members[rm.id] = rm
                    if rm.status == ALIVE:
                        fired.append(("member-join", rm))
                    elif rm.status == FAILED:
                        fired.append(("member-failed", rm))
                    continue
                if rm.incarnation > cur.incarnation or (
                    rm.incarnation == cur.incarnation
                    and _STATUS_RANK[rm.status] > _STATUS_RANK[cur.status]
                ):
                    was = cur.status
                    cur.status = rm.status
                    cur.incarnation = rm.incarnation
                    cur.tags = dict(rm.tags)
                    cur.addr = rm.addr
                    if rm.status == ALIVE:
                        self._suspect_since.pop(rm.id, None)
                    if was != rm.status:
                        if rm.status == ALIVE:
                            fired.append(("member-join", cur))
                        elif rm.status == FAILED:
                            fired.append(("member-failed", cur))
                        elif rm.status == LEFT:
                            fired.append(("member-leave", cur))
        for kind, m in fired:
            self._fire(kind, m)
        if refute:
            logger.info("%s: refuted failure rumor", self.node_id)

    def _fire(self, kind: str, member: Member) -> None:
        if self.on_event is not None:
            try:
                self.on_event(kind, member)
            except Exception:
                logger.exception("membership event handler failed")
