"""Scheduler workers: dequeue evals, invoke a scheduler, submit plans.

Reference: nomad/worker.go — run :105, dequeueEvaluation :142,
snapshotMinIndex :228, invokeScheduler :244, SubmitPlan :277 (the Planner
implementation backed by the plan queue).

Two worker flavors:
  * Worker — the reference-shaped loop: one eval at a time through the
    scheduler factory (host or TPU backend per SchedulerConfig).
  * TPUBatchWorker — drains many ready evals and solves them in ONE tensor
    batch (scheduler/tpu solve_eval_batch), submitting one plan per eval.
    This is what the ≥20x throughput target rides on: the broker's per-job
    serialization still holds (each dequeued eval is a different job).
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from concurrent.futures import CancelledError
from typing import Optional

from .. import metrics, trace
from ..retry import WORKER_POLICY
from ..scheduler import new_scheduler
from ..scheduler.context import SchedulerConfig
from ..structs import Evaluation, Plan, PlanResult
from .. import faultplane
from .raft_replication import NotLeaderError

logger = logging.getLogger("nomad_tpu.worker")

DEQUEUE_TIMEOUT_S = 0.5


def _retriable_device_error(e: BaseException) -> bool:
    """Classify a device-stage failure: retriable ⇒ the batch falls back
    to the host solve path (a sick device degrades throughput instead of
    wedging the pipeline); terminal ⇒ the existing nack path. XLA
    runtime errors (device OOM, halted chip, transfer failure) are
    retriable — the host oracle needs no device. Injected chaos faults
    carry their own classification."""
    if isinstance(e, faultplane.DeviceFault):
        return e.retriable
    return type(e).__name__ == "XlaRuntimeError"


class Backpressure:
    """Couples the TPU worker's drain/batch sizing to the plan-apply
    side's health: plan-queue depth (the applier's backlog) and an EWMA
    of plan-submit latency (queue wait + verify + raft apply as the
    worker sees it). Without this, the pipelined solve stage keeps
    inflating batches an overwhelmed applier can't drain — queue depth
    and commit latency grow without bound while the solver reports
    great throughput (the overload failure mode ROADMAP item 3 names).

    Policy: depth <= queue_hwm runs at the configured batch size; each
    unit past the hwm halves the batch (floor 1); depth >= stall_depth
    pauses dequeue entirely until the applier catches up. A submit-
    latency EWMA past latency_hwm_s halves the batch once more —
    latency-based coupling catches a slow-but-shallow queue (fsync
    stalls under fault injection) that depth alone misses."""

    def __init__(
        self,
        queue_hwm: int = 2,
        stall_depth: int = 8,
        latency_hwm_s: float = 5.0,
        alpha: float = 0.3,
    ) -> None:
        self.queue_hwm = queue_hwm
        self.stall_depth = stall_depth
        self.latency_hwm_s = latency_hwm_s
        self.alpha = alpha
        self._ewma_s = 0.0

    def note_submit_latency(self, dt_s: float) -> None:
        self._ewma_s = (
            dt_s
            if self._ewma_s == 0.0
            else self.alpha * dt_s + (1 - self.alpha) * self._ewma_s
        )

    @property
    def submit_ewma_s(self) -> float:
        return self._ewma_s

    def should_stall(self, queue_depth: int) -> bool:
        return queue_depth >= self.stall_depth

    def batch_limit(self, configured: int, queue_depth: int) -> int:
        limit = configured
        if queue_depth > self.queue_hwm:
            limit = max(1, configured >> (queue_depth - self.queue_hwm))
        if self._ewma_s > self.latency_hwm_s:
            limit = max(1, limit // 2)
        # level: 0 = wide open, 1 = fully stalled (for `operator top`)
        level = min(1.0, max(
            queue_depth / max(1, self.stall_depth),
            0.0 if self.latency_hwm_s <= 0
            else min(1.0, self._ewma_s / (2 * self.latency_hwm_s)),
        ))
        metrics.set_gauge("nomad.worker.backpressure_level", level)
        metrics.set_gauge("nomad.worker.batch_limit", limit)
        return limit


class WorkerPlanner:
    """Planner interface backed by the server's plan queue + raft apply.
    ``on_submit_latency`` — optional hook (the TPU worker installs its
    Backpressure.note_submit_latency) fed every plan-submit wall time."""

    def __init__(self, server) -> None:
        self.server = server
        self.on_submit_latency = None

    def submit_plan(self, plan: Plan):
        ctx = trace.current()
        t0 = time.perf_counter()
        with trace.span(ctx, "plan.submit") as h:
            tref = (ctx, h.span) if ctx is not None else None
            fut = self.server.plan_queue.enqueue(plan, trace_ctx=tref)
            result: PlanResult = fut.result(timeout=30)
        # queue wait + verify + raft apply, as the worker saw it
        dt = time.perf_counter() - t0
        metrics.observe("nomad.plan.submit_seconds", dt)
        if self.on_submit_latency is not None:
            self.on_submit_latency(dt)
        new_state = None
        if result.refresh_index > 0:
            with trace.span(ctx, "snapshot.refresh"):
                new_state = self.server.state.snapshot_min_index(
                    result.refresh_index, timeout_s=5
                )
        return result, new_state

    def submit_plan_batch(self, plans: list[Plan]) -> list[PlanResult]:
        """Submit a whole batch of same-snapshot plans as one queue item;
        the applier merges the node-disjoint subset into a single raft
        apply (plan_apply.py). One snapshot wait covers every partial
        commit in the batch, so retry evals never race their own
        refresh index."""
        ctx = trace.current()
        t0 = time.perf_counter()
        with trace.span(ctx, "plan.submit", plans=len(plans)) as h:
            tref = (ctx, h.span) if ctx is not None else None
            futs = self.server.plan_queue.enqueue_batch(
                plans, trace_ctx=tref
            )
            results: list[PlanResult] = [f.result(timeout=60) for f in futs]
        dt = time.perf_counter() - t0
        metrics.observe("nomad.plan.submit_seconds", dt)
        if self.on_submit_latency is not None:
            self.on_submit_latency(dt)
        max_refresh = max((r.refresh_index for r in results), default=0)
        if max_refresh > 0:
            with trace.span(ctx, "snapshot.refresh"):
                self.server.state.snapshot_min_index(
                    max_refresh, timeout_s=5
                )
        return results

    def update_eval(self, eval_obj: Evaluation) -> None:
        self.server.raft_apply("eval_update", [eval_obj])

    def create_eval(self, eval_obj: Evaluation) -> None:
        self.server.raft_apply("eval_update", [eval_obj])

    def refresh_state(self, min_index: int):
        return self.server.state.snapshot_min_index(min_index, timeout_s=5)


class Worker:
    def __init__(
        self,
        server,
        schedulers: list[str],
        config: Optional[SchedulerConfig] = None,
        name: str = "worker",
    ) -> None:
        self.server = server
        self.schedulers = schedulers
        self.config = config or SchedulerConfig()
        self.name = name
        self.planner = WorkerPlanner(server)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.processed = 0

    def start(self) -> None:
        # Fresh Event per incarnation: a thread that outlives join(timeout)
        # (e.g. blocked in submit_plan) polls ITS event and still exits,
        # instead of seeing a cleared shared flag and resuming as a twin.
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(self._stop,), daemon=True, name=self.name
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: float = 2.0) -> None:
        if self._thread:
            self._thread.join(timeout)

    def _run(self, stop: threading.Event) -> None:
        broker = self.server.eval_broker
        # NotLeaderError backoff (retry.py): during a revoke window the
        # broker still hands out evals for a beat, and every submit
        # fails NotLeaderError — without backoff this loop nacks and
        # redequeues at full speed (the hot loop the chaos harness
        # reproduces with a leader kill). Resets on the next success.
        backoff = WORKER_POLICY.backoff()
        while not stop.is_set():
            ev, token = broker.dequeue(self.schedulers, timeout_s=DEQUEUE_TIMEOUT_S)
            if ev is None:
                continue
            t0 = time.perf_counter()
            try:
                with trace.use(broker.trace_context(ev.id)):
                    self._process(ev)
                backoff.reset()
            except (Exception, CancelledError) as e:
                # CancelledError included: a leadership revoke disables
                # the plan queue mid-submit and the cancelled future
                # raises BaseException — it must nack and back off, not
                # kill the worker thread with the eval un-nacked.
                logger.exception("%s: eval %s failed", self.name, ev.id)
                metrics.incr("nomad.worker.invoke.failed")
                try:
                    broker.nack(ev.id, token)
                except ValueError:
                    pass
                if isinstance(e, (NotLeaderError, CancelledError)):
                    metrics.incr("nomad.rpc.retry_count.worker.invoke")
                    stop.wait(backoff.next())
                continue
            # reference telemetry: nomad.worker.invoke_scheduler.<type>
            metrics.observe(
                f"nomad.worker.invoke_seconds.{ev.type}",
                time.perf_counter() - t0,
            )
            try:
                broker.ack(ev.id, token)
            except ValueError:
                pass
            self.processed += 1

    def _process(self, ev: Evaluation) -> None:
        ctx = trace.current()
        # Wait until our snapshot has caught up to the eval's creation
        # (reference: worker.go:121 snapshotMinIndex).
        wait_index = max(ev.modify_index, ev.snapshot_index)
        with trace.span(ctx, "snapshot.wait", index=wait_index):
            snapshot = self.server.state.snapshot_min_index(
                wait_index, timeout_s=5
            )
        if ev.type == "_core":
            # GC evals dispatch to the CoreScheduler, which mutates state
            # through the server's raft rather than submitting plans
            # (reference worker.go invokeScheduler: eval.Type == "_core").
            from .core_sched import CoreScheduler

            CoreScheduler(self.server, snapshot).process(ev)
            # Core evals are broker-only, never persisted (reference
            # leader.go schedulePeriodic enqueues without Raft) — acking
            # is all the cleanup they need.
            return
        sched = new_scheduler(ev.type, logger, snapshot, self.planner, self.config)
        with trace.span(ctx, "scheduler.invoke", type=ev.type):
            sched.process(ev)


class TPUBatchWorker:
    """Drains up to `batch_size` ready evals per cycle and solves them in
    one batched tensor program.

    Two-stage pipeline (docs/pipeline.md): the SOLVE stage (this worker's
    main thread) dequeues a batch, snapshots, and runs the device solve;
    the COMMIT stage (a dedicated thread) materializes plan submission,
    eval updates, and ack/nack. A bounded handoff queue of depth 1 means
    batch N+1's dequeue/lower/device dispatch overlaps batch N's plan
    commit — the same depth-1 optimistic overlap the reference plan
    applier runs (plan_apply.go:54-63), won here at the worker layer
    where the GIL releases during the device round-trip. `pipeline=False`
    degrades to the old solve-then-commit loop (the bench's
    non-overlapped comparator)."""

    def __init__(
        self,
        server,
        schedulers: list[str] = ("service", "batch"),
        batch_size: int = 64,
        config: Optional[SchedulerConfig] = None,
        pipeline: bool = True,
        lane_priority: Optional[int] = None,
    ) -> None:
        import os

        self.server = server
        self.schedulers = list(schedulers)
        self.batch_size = batch_size
        self.config = config or SchedulerConfig(backend="tpu")
        self.planner = WorkerPlanner(server)
        # Interactive priority lane (docs/pipeline.md § Priority lanes):
        # evals at or above this priority never wait for — or ride in —
        # a mega-batch. They preempt the drain stage, solve alone
        # (usually via the host microsolve), and commit inline on the
        # solve thread, jumping ahead of the in-flight batch's commit.
        # Mirrors the round-11 admission classification: the broker
        # displaces strictly-below-priority work; the lane fast-paths
        # strictly-above-default work. 0 disables the lane.
        if lane_priority is None:
            lane_priority = int(
                os.environ.get("NOMAD_TPU_LANE_PRIORITY", "60") or 0
            )
        self.lane_priority = lane_priority
        # an interactive eval pulled mid-drain, solved FIRST next
        # cycle: (eval, token, hold time — its running lane clock)
        self._held: Optional[tuple[Evaluation, str, float]] = None
        # Interactive-placement ledger: (raft index, {node_id: (cpu,
        # mem, disk)}) per lane commit that landed while a mega-batch
        # chain was in flight. A chained solve supersedes the committed
        # aggregate with the parent's used' tensor, which never saw
        # these placements — the ledger feeds them back as usage deltas
        # (solver extra_usage) so a jumped eval still places
        # conflict-free with its chained followers.
        self._lane_ledger: list[tuple[int, dict]] = []
        # plan-apply backpressure: the solve stage sizes (and stalls)
        # its drains from the applier's queue depth + submit latency
        self.backpressure = Backpressure()
        self.planner.on_submit_latency = self.backpressure.note_submit_latency
        self.pipeline = pipeline
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cthread: Optional[threading.Thread] = None
        # depth-1 handoff: at most ONE solved batch awaits commit while
        # the next batch solves
        self._commit_q: "queue_mod.Queue" = queue_mod.Queue(maxsize=1)
        # (pending, committed_event, outcome, basis_index) of the batch
        # handed to the commit stage: while its commit is in flight, the
        # next solve chains on its device-resident used' tensor
        # (solver.py used_chain) so the two batches place conflict-free.
        # basis_index is the chain's transitive capacity basis (the
        # oldest chained ancestor's snapshot index).
        self._prev: Optional[tuple] = None
        self.processed = 0
        # Multi-chip (config.mesh_devices > 1): one ResidentClusterState
        # per worker, sharded over the mesh — resident tensors are
        # placed per-shard once and steady-state solves ship only usage
        # deltas into the owning shard. Built lazily at the first solve
        # (jax stays unloaded until the TPU path actually runs).
        self._resident = None
        # Solver-pool tier (server/solver_pool.py): when the cluster
        # attaches a tracker here, mega-batch drains dispatch to warm
        # remote members instead of the local device; the interactive
        # lane and the empty-pool case keep the local path. None on a
        # standalone Server (no cluster/pool).
        self.solver_pool = None
        # Shared NotLeaderError backoff across the commit stage (see
        # Worker._run): a revoke window must throttle, not hot-loop.
        self._nl_backoff = WORKER_POLICY.backoff()

    def _ensure_resident(self) -> None:
        """Build the (possibly mesh-sharded) ResidentClusterState at the
        first solve — jax stays unloaded until the TPU path actually
        runs. A misconfigured mesh (NOMAD_TPU_MESH_DEVICES beyond what
        the backend exposes) must NOT raise here: the exception would
        nack and redeliver every eval forever — the cluster accepts
        jobs but never places. Degrade loudly to single-chip instead,
        and clear mesh_devices so the scheduler's _mesh_for doesn't
        re-raise the same error per solve.

        Single-chip workers get a plain ResidentClusterState too (new
        with the interactive fast path): beyond the resident device
        tensors it carries the WARM EVAL CONTEXT — the cached ready-node
        lists, host-table skeleton, and lowered-group skeletons that let
        a repeat-shaped interactive eval skip the node scan and lowering
        entirely (solver.py)."""
        if self._resident is not None:
            return
        from ..scheduler.tpu import ResidentClusterState

        if (getattr(self.config, "mesh_devices", 0) or 0) <= 1:
            self._resident = ResidentClusterState()
            return
        from ..scheduler.tpu.sharding import solver_mesh

        try:
            self._resident = ResidentClusterState(
                mesh=solver_mesh(self.config.mesh_devices)
            )
        except RuntimeError as exc:
            logger.error(
                "mesh_devices=%d unusable (%s); falling back to the "
                "single-chip solver — fix NOMAD_TPU_MESH_DEVICES or "
                "the backend's device count",
                self.config.mesh_devices, exc,
            )
            self.config.mesh_devices = 0
            self._resident = ResidentClusterState()

    def start(self) -> None:
        # Fresh Event + queue per incarnation (see Worker.start).
        self._stop = threading.Event()
        self._commit_q = queue_mod.Queue(maxsize=1)
        self._prev = None
        self._held = None
        self._lane_ledger = []
        self._thread = threading.Thread(
            target=self._run, args=(self._stop,), daemon=True,
            name="tpu-batch-solve"
        )
        self._thread.start()
        if self.pipeline:
            self._cthread = threading.Thread(
                target=self._commit_loop,
                args=(self._stop, self._commit_q),
                daemon=True,
                name="tpu-batch-commit",
            )
            self._cthread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        if self._cthread:
            # Sentinel AFTER the solve thread is down: the commit thread
            # drains every batch handed off before it (FIFO) and exits on
            # the sentinel itself — a stop racing the hand-off can never
            # strand a solved batch between the two threads' stop checks
            # (un-acked evals would hold the broker's per-job locks
            # forever; only ack/nack release them).
            try:
                self._commit_q.put(None, timeout=15)
            except queue_mod.Full:  # pragma: no cover - commit thread dead
                pass
            self._cthread.join(timeout=15)
            self._cthread = None
        # a zombie solve thread that outlived join(5) above could still
        # have slipped one batch in after the sentinel: nack it so its
        # evals redeliver instead of leaking their job locks
        while True:
            try:
                item = self._commit_q.get_nowait()
            except queue_mod.Empty:
                break
            if item is not None:
                (batch, _pending, _snapshot, committed, outcome,
                 _chain, bctx, _t_deq) = item
                self._nack_batch(batch)
                outcome["ok"] = False
                committed.set()
                if bctx is not None:
                    bctx.finish("stopped")
        # a held interactive eval never reached a solve: nack it so its
        # job's broker lock releases instead of leaking
        if self._held is not None:
            held, self._held = self._held, None
            self._nack_batch([held[:2]])
        # a stopped worker object stays referenced by the server; don't
        # let it pin the last batch's device tensors and snapshot
        self._prev = None

    def stats_snapshot(self) -> dict:
        """Live pipeline depth for /v1/solver/status and the operator-top
        solver panel (same idiom as the broker/plan-queue stats
        surfaces): reads live structures only, no locks beyond the
        queue's own."""
        prev = self._prev
        return {
            "pipeline": self.pipeline,
            "batch_size": self.batch_size,
            "processed": self.processed,
            "schedulers": list(self.schedulers),
            "commit_queue_depth": self._commit_q.qsize(),
            "chain_in_flight": bool(prev is not None and not prev[1].is_set()),
            "held_interactive": self._held is not None,
            "lane_ledger_len": len(self._lane_ledger),
            "submit_ewma_s": round(self.backpressure.submit_ewma_s, 6),
            "lane_priority": self.lane_priority,
        }

    # -- solve stage ----------------------------------------------------

    def _interactive(self, ev: Evaluation) -> bool:
        """Priority-lane classification: at or above the lane priority
        an eval is interactive — it never waits for, or rides in, a
        mega-batch (the round-11 admission classification's mirror:
        admission displaces strictly-below work; the lane fast-paths
        above-default work)."""
        return self.lane_priority > 0 and ev.priority >= self.lane_priority

    def _run(self, stop: threading.Event) -> None:
        broker = self.server.eval_broker
        while not stop.is_set():
            # Drop the previous batch's PendingEvalBatch once its commit
            # lands: on an idle worker it would otherwise pin the solved
            # batch's device tensors, node tables, and snapshot until the
            # next eval arrives.
            if self._prev is not None and self._prev[1].is_set():
                self._prev = None
            # Backpressure gate BEFORE the blocking dequeue: while the
            # plan queue is saturated, solving more batches only grows
            # the backlog the applier is already failing to drain — the
            # evals are safer waiting in the broker (sheddable,
            # priority-ordered) than baked into solved-but-uncommitted
            # plans.
            stalled = False
            while not stop.is_set() and self.backpressure.should_stall(
                self.server.plan_queue.depth()
            ):
                if not stalled:
                    stalled = True
                    metrics.incr("nomad.worker.backpressure_throttled")
                stop.wait(0.05)
            if stop.is_set():
                break
            batch: list[tuple[Evaluation, str]] = []
            t_deq = None
            if self._held is not None:
                # the interactive eval that preempted the last drain —
                # its lane clock started when it was HELD, so the time
                # it waited through the preempting batch's phase A
                # counts (lane starvation must read off the histogram)
                ev, token, t_deq = self._held
                self._held = None
            else:
                ev, token = broker.dequeue(
                    self.schedulers, timeout_s=DEQUEUE_TIMEOUT_S
                )
            if ev is None:
                continue
            if t_deq is None:
                t_deq = time.perf_counter()
            if self._interactive(ev):
                self._run_interactive(ev, token, t_deq)
                continue
            batch.append((ev, token))
            # Effective batch size under backpressure: plan-queue depth
            # and submit-latency EWMA shrink the drain so the solver
            # stops inflating batches the applier can't absorb.
            limit = self.backpressure.batch_limit(
                self.batch_size, self.server.plan_queue.depth()
            )
            if limit < self.batch_size:
                metrics.incr("nomad.worker.backpressure_throttled")
            # One trace per BATCH (the per-eval broker traces link to it
            # via the batch attr): solve/commit stage spans are shared
            # across the whole batch, so duplicating them per eval would
            # multiply span volume by batch_size for no information.
            bctx = trace.start_trace("tpu.batch")
            with trace.span(bctx, "broker.drain"):
                # opportunistically drain more ready evals without waiting
                while len(batch) < limit:
                    ev2, token2 = broker.dequeue(
                        self.schedulers, timeout_s=0.01
                    )
                    if ev2 is None:
                        break
                    if self._interactive(ev2):
                        # lane preempts the drain: the interactive eval
                        # is never baked into this mega-batch — it jumps
                        # the line as its own solve next cycle (held
                        # with its lane clock already running)
                        self._held = (ev2, token2, time.perf_counter())
                        metrics.incr("nomad.worker.lane.drain_preempted")
                        break
                    batch.append((ev2, token2))
            if bctx is not None:
                bctx.set_attr("evals", len(batch))
                bctx.set_attr("eval_ids", [e.id for e, _ in batch])
                bctx.set_attr(
                    "job_ids", sorted({e.job_id for e, _ in batch})
                )
                for e, _ in batch:
                    broker.annotate_trace(e.id, batch=bctx.trace_id)
            try:
                with trace.use(bctx):
                    with trace.span(bctx, "solve.dispatch"):
                        pending, snapshot, chained_on = self._solve_batch(
                            [e for e, _ in batch]
                        )
            except Exception:
                logger.exception("tpu batch solve of %d failed", len(batch))
                metrics.incr("nomad.worker.invoke.failed")
                self._nack_batch(batch)
                if bctx is not None:
                    bctx.finish("solve-failed")
                continue
            # outcome["ok"] is the commit verdict the NEXT batch (which
            # may have chained on this one's used' tensor) branches on:
            # True/False once decided, None while in flight. FIFO commit
            # order guarantees it is decided before the child commits.
            outcome: dict = {"ok": None}
            if not self.pipeline:
                self._commit(
                    batch, pending, snapshot, threading.Event(),
                    outcome, chained_on, bctx, t_deq=t_deq,
                )
                continue
            committed = threading.Event()
            handed_off = False
            hspan = trace.span(bctx, "commit.handoff")
            hspan.__enter__()
            while not stop.is_set():
                try:
                    self._commit_q.put(
                        (batch, pending, snapshot, committed,
                         outcome, chained_on, bctx, t_deq),
                        timeout=0.2,
                    )
                    handed_off = True
                    break
                except queue_mod.Full:
                    continue
            hspan.__exit__(None, None, None)
            if not handed_off:
                # stopping with a solved batch that never reached the
                # commit stage: nack so the evals redeliver cleanly
                self._nack_batch(batch)
                outcome["ok"] = False
                if bctx is not None:
                    bctx.finish("stopped")
            else:
                # this batch's effective capacity basis: its own snapshot
                # unless it chained, in which case the chain's basis
                # propagates TRANSITIVELY (a chain_out tensor built on a
                # chained input is still based on the oldest ancestor's
                # snapshot — external capacity events since then are
                # masked for every descendant)
                basis = chained_on[1] if chained_on else snapshot.index
                self._prev = (pending, committed, outcome, basis)

    def _run_interactive(self, ev: Evaluation, token: str,
                         t_deq: float) -> None:
        """The interactive lane: solve one eval alone — no drain, no
        mega-batch — and commit INLINE on the solve thread, jumping
        ahead of the in-flight batch sitting in the commit queue. Small
        evals resolve via the host microsolve (zero device round-trip);
        big high-priority evals still skip the drain wait. The used'
        chain composes through the lane ledger: a committed lane
        placement that the live chain tensor never saw is fed back to
        the next chained solve as usage deltas (_solve_batch)."""
        metrics.incr("nomad.worker.lane.interactive")
        batch = [(ev, token)]
        bctx = trace.start_trace("tpu.interactive")
        if bctx is not None:
            bctx.set_attr("eval_id", ev.id)
            bctx.set_attr("job_id", ev.job_id)
            self.server.eval_broker.annotate_trace(
                ev.id, batch=bctx.trace_id
            )
        try:
            with trace.use(bctx):
                with trace.span(bctx, "solve.dispatch"):
                    # allow_chain=False: the lane commits INLINE, ahead
                    # of the in-flight parent — a chained solve here
                    # would break the FIFO guarantee that a parent's
                    # commit verdict is decided before its child's. The
                    # solve sees committed state (+ the lane ledger);
                    # the applier's verification trims any conflict
                    # with the still-uncommitted mega batch.
                    pending, snapshot, chained_on = self._solve_batch(
                        [ev], allow_chain=False
                    )
        except Exception:
            logger.exception("interactive solve of %s failed", ev.id)
            metrics.incr("nomad.worker.invoke.failed")
            self._nack_batch(batch)
            if bctx is not None:
                bctx.finish("solve-failed")
            return
        if pending.used_micro:
            metrics.incr("nomad.worker.lane.micro")
        outcome: dict = {"ok": None}
        try:
            self._commit(
                batch, pending, snapshot, threading.Event(), outcome,
                chained_on, bctx, lane="interactive", t_deq=t_deq,
            )
        except (Exception, CancelledError):
            # same backstop as _commit_loop: an escape past _commit's
            # own guards (e.g. in the post-commit lane bookkeeping)
            # must nack, not kill the solve thread — a dead solve
            # thread silently stops ALL scheduling until restart
            logger.exception("interactive commit stage hard failure")
            self._nack_batch(batch)
            outcome["ok"] = False
            if bctx is not None:
                bctx.finish("commit-failed")

    def _lane_extra_usage(self, snapshot, chained_on) -> Optional[dict]:
        """Merge lane-ledger placements this solve's capacity view would
        otherwise miss: everything newer than the chain basis (a chained
        solve reads the parent's used' tensor, frozen at the basis) or —
        unchained — newer than the snapshot. Entries old enough for
        every future view are pruned; over-inclusion in the race windows
        is deliberate (counting a visible placement twice under-fills,
        which the applier's verification never has to repair)."""
        cutoff = (
            chained_on[1] if chained_on is not None else snapshot.index
        )
        if not self._lane_ledger:
            return None
        keep = min(cutoff, snapshot.index)
        if self._prev is not None and not self._prev[1].is_set():
            # a LIVE chain pins the prune horizon: this solve may not
            # need an entry, but the next chained solve reads from the
            # in-flight parent's (older) basis and still does
            keep = min(keep, self._prev[3])
        if keep > 0:
            self._lane_ledger = [
                e for e in self._lane_ledger if e[0] > keep
            ]
        merged: dict[str, tuple] = {}
        for idx, deltas in self._lane_ledger:
            if idx <= cutoff:
                continue
            for nid, v in deltas.items():
                cur = merged.get(nid)
                merged[nid] = (
                    v
                    if cur is None
                    else (cur[0] + v[0], cur[1] + v[1], cur[2] + v[2])
                )
        return merged or None

    @staticmethod
    def _plan_usage_deltas(plans: dict) -> dict:
        """Per-node (cpu, mem, disk) usage added by a set of plans —
        eager rows and SoA batch columns alike (stops are ignored:
        under-counting freed capacity only under-fills)."""
        out: dict[str, list] = {}
        for plan in plans.values():
            for nid, allocs in plan.node_allocation.items():
                for a in allocs:
                    r = a.comparable_resources()
                    d = out.get(nid)
                    if d is None:
                        d = out[nid] = [0, 0, 0]
                    d[0] += r.cpu
                    d[1] += r.memory_mb
                    d[2] += r.disk_mb
            for b in plan.alloc_batches:
                c = b.row_contribution()
                for nid, _ti, cnt in b.touched_nodes():
                    d = out.get(nid)
                    if d is None:
                        d = out[nid] = [0, 0, 0]
                    d[0] += c[0] * cnt
                    d[1] += c[1] * cnt
                    d[2] += c[2] * cnt
        return {k: tuple(v) for k, v in out.items()}

    def _solve_batch(self, evals: list[Evaluation],
                     allow_chain: bool = True):
        """Phase A: snapshot + reconcile + lower + async device dispatch.
        Returns the PendingEvalBatch whose finish() (run on the commit
        stage) blocks on the device and materializes the plans.
        allow_chain=False (the interactive lane) never consumes the
        in-flight parent's used' tensor — lane solves commit ahead of
        the parent, outside the FIFO the chain verdict relies on."""
        from ..scheduler.tpu import solve_eval_batch_begin

        wait_index = max(
            max(ev.modify_index for ev in evals),
            max(ev.snapshot_index for ev in evals),
        )
        with trace.span(trace.current(), "snapshot.wait", index=wait_index):
            snapshot = self.server.state.snapshot_min_index(
                wait_index, timeout_s=5
            )
        # Chain on the in-flight batch's post-solve usage tensor ONLY
        # while its commit is pending: once committed, the snapshot's
        # aggregate already carries those placements and the chain would
        # just mask newer external writes.
        chain = None
        chained_on = None
        if self._prev is not None:
            prev_pending, committed, prev_outcome, prev_basis = self._prev
            if committed.is_set():
                # drop a committed parent regardless of lane: a stream
                # of interactive solves must not keep the last mega
                # batch's device tensors and snapshot pinned
                self._prev = None
            elif allow_chain:
                chain = prev_pending.chain
                # (parent's commit-verdict holder, the chain's BASIS
                # index). The basis is the parent's own basis — NOT its
                # snapshot index — so it propagates transitively through
                # multi-hop chains: capacity freed after the oldest
                # ancestor's snapshot is masked by the chained used'
                # tensor, so any blocked eval this solve mints must watch
                # for unblocks from that index or a capacity event in the
                # gap is treated as already seen and the eval strands.
                chained_on = (prev_outcome, prev_basis)
        t0 = time.perf_counter()
        if faultplane.plane is not None:
            # injected dispatch-stage fault: surfaces through the solve
            # stage's existing failure path (nack + redeliver)
            faultplane.plane.on_device("dispatch")
        # Dispatch policy (docs/solver-pool.md): mega-batch drains route
        # to the solver pool when a healthy member exists; the
        # interactive lane (allow_chain=False — the host-microsolve
        # path) always solves locally. A remote batch never consumes
        # the local used' chain: overlapping remote solves serialize
        # through the applier's plan verification instead, so
        # chained_on is dropped (the parent's verdict must not nack a
        # batch that never saw its tensor).
        if allow_chain and self.solver_pool is not None:
            with trace.span(
                trace.current(), "solver.pool.dispatch", evals=len(evals)
            ):
                remote = self.solver_pool.dispatch_batch(
                    evals, snapshot, self.planner, self.config,
                    extra_usage=self._lane_extra_usage(snapshot, None),
                )
            if remote is not None:
                metrics.observe("nomad.tpu.batch_evals", len(evals))
                metrics.observe(
                    "nomad.tpu.batch_dispatch_seconds",
                    time.perf_counter() - t0,
                )
                return remote, snapshot, None
        self._ensure_resident()
        pending = solve_eval_batch_begin(
            snapshot, self.planner, evals, self.config, used_chain=chain,
            resident=self._resident,
            extra_usage=self._lane_extra_usage(snapshot, chained_on),
        )
        if chained_on is not None and not pending.chain_accepted:
            # the solver took a path that never consumed the chain (host
            # partition, resident tensors, node-universe mismatch): this
            # solve saw only committed state, so the parent's commit
            # verdict must not nack it and its blocked evals need no
            # older basis index
            chained_on = None
        metrics.observe("nomad.tpu.batch_evals", len(evals))
        metrics.observe(
            # renamed from batch_solve_seconds when the pipeline split
            # landed: this now times ONLY phase A (reconcile + lower +
            # async dispatch) — device wait and materialization moved to
            # the commit stage's device/materialize/commit timers
            "nomad.tpu.batch_dispatch_seconds", time.perf_counter() - t0
        )
        return pending, snapshot, chained_on

    # -- commit stage ---------------------------------------------------

    def _commit_loop(
        self, stop: threading.Event, cq: "queue_mod.Queue"
    ) -> None:
        # Exits ONLY on the stop() sentinel, never on a bare stop-flag
        # check: the FIFO guarantees every batch handed off before the
        # sentinel is committed (or nacked by _commit's failure path)
        # first, so no solved batch is ever stranded with its evals
        # un-acked.
        while True:
            item = cq.get()
            if item is None:
                return
            (batch, pending, snapshot, committed, outcome,
             chained_on, bctx, t_deq) = item
            try:
                self._commit(
                    batch, pending, snapshot, committed, outcome,
                    chained_on, bctx, t_deq=t_deq,
                )
            except (Exception, CancelledError):
                # _commit has its own guards; this is the backstop that
                # keeps the commit thread alive no matter what — a dead
                # commit thread strands every later batch with its evals
                # un-acked (per-job broker locks leak forever)
                logger.exception("tpu commit stage hard failure")
                self._nack_batch(batch)
                outcome["ok"] = False
                committed.set()
                if bctx is not None:
                    bctx.finish("commit-failed")

    def _nack_batch(self, batch: list[tuple[Evaluation, str]]) -> None:
        broker = self.server.eval_broker
        for ev_, tok in batch:
            try:
                broker.nack(ev_.id, tok)
            except ValueError:
                pass

    def _commit(
        self, batch, pending, snapshot, committed, outcome, chained_on,
        bctx=None, lane: str = "batch", t_deq: Optional[float] = None,
    ) -> None:
        broker = self.server.eval_broker
        if chained_on is not None and chained_on[0].get("ok") is False:
            # This batch solved against the used' tensor of a batch whose
            # commit then FAILED: its view baked in placements that never
            # landed, so near-full nodes look occupied that are free —
            # committing would mint blocked evals waiting on a capacity
            # event that never comes. Nack instead: the evals redeliver
            # and re-solve against a clean snapshot. (FIFO commit order
            # means the parent's verdict is always decided by now.)
            metrics.incr("nomad.tpu.chain_parent_failed")
            self._nack_batch(batch)
            outcome["ok"] = False
            committed.set()
            if bctx is not None:
                bctx.finish("chain-parent-failed")
            return
        used_fallback = False
        try:
            with trace.use(bctx):
                # phase B: block on the device, read back, materialize
                # plans (device/readback/materialize stage timers become
                # spans via the solver's trace.stage calls); then the
                # plan submit is timed as the commit stage proper
                try:
                    with trace.span(bctx, "commit.finish"):
                        if faultplane.plane is not None:
                            faultplane.plane.on_device("finish")
                        plans = pending.finish()
                except (Exception, CancelledError) as de:
                    if not _retriable_device_error(de):
                        raise
                    # Graceful degradation: the device stage died but the
                    # batch's reconcile output is intact — re-solve the
                    # same asks on the host oracle path. A sick device
                    # costs throughput, not the pipeline.
                    logger.warning(
                        "device stage failed (%s: %s); falling back to "
                        "host solve for %d evals",
                        type(de).__name__, de, len(batch),
                    )
                    metrics.incr("nomad.worker.device_failover")
                    with trace.span(
                        bctx, "device.failover", error=type(de).__name__
                    ):
                        plans = pending.solve_host_fallback()
                    used_fallback = True
                t0 = time.perf_counter()
                all_full = self._commit_batch(
                    [e for e, _ in batch], plans, snapshot,
                    blocked_basis=chained_on[1] if chained_on else None,
                )
        except (Exception, CancelledError) as e:
            # CancelledError included: plan futures cancelled by a queue
            # disable (leadership loss) are BaseException since py3.8 and
            # must still nack, not kill the commit thread
            logger.exception("tpu batch commit of %d failed", len(batch))
            metrics.incr("nomad.worker.invoke.failed")
            self._nack_batch(batch)
            outcome["ok"] = False
            if bctx is not None:
                bctx.finish("commit-failed")
            if isinstance(e, (NotLeaderError, CancelledError)):
                # leadership churn: throttle instead of hot-looping the
                # solve→commit→nack cycle until the revoke lands
                metrics.incr("nomad.rpc.retry_count.worker.submit")
                self._stop.wait(self._nl_backoff.next())
            return
        finally:
            # chain cutoff: the solve stage stops chaining on this batch
            # the moment its effects are (or will never be) committed
            committed.set()
        self._nl_backoff.reset()
        # A partial commit is a failed verdict for chaining purposes: the
        # trimmed placements are in the chained used' tensor but never
        # landed, so a follower that baked them in must re-solve too.
        # A host fallback is too: the committed placements came from the
        # host oracle, not the device tensor a chained child consumed.
        outcome["ok"] = all_full and not used_fallback
        # commit_seconds joins the solver's host_prep/device/readback/
        # materialize stage registry: the full commit half of the pipeline
        metrics.observe(
            "nomad.tpu.commit_seconds", time.perf_counter() - t0
        )
        if lane == "interactive":
            # lane-ledger record: an interactive commit that landed
            # while a mega-batch chain is in flight is invisible to the
            # chained used' tensor — remember its per-node deltas so the
            # next chained solve counts them (committed.is_set() is the
            # chain cutoff the solve stage branches on; runs on the
            # solve thread, so the ledger stays single-threaded)
            if self._prev is not None and not self._prev[1].is_set():
                deltas = self._plan_usage_deltas(plans)
                if deltas:
                    self._lane_ledger.append(
                        (self.server.state.latest_index(), deltas)
                    )
                    del self._lane_ledger[:-64]
        if t_deq is not None:
            lane_dt = time.perf_counter() - t_deq
            if lane == "interactive":
                metrics.observe(
                    "nomad.worker.lane.interactive_seconds", lane_dt
                )
            else:
                metrics.observe("nomad.worker.lane.batch_seconds", lane_dt)
        with trace.span(bctx, "eval.ack"):
            for ev_, tok in batch:
                try:
                    broker.ack(ev_.id, tok)
                except ValueError:
                    pass
        if bctx is not None:
            bctx.finish("ok" if all_full else "partial")
        self.processed += len(batch)

    def _commit_batch(
        self, evals: list[Evaluation], plans, snapshot,
        blocked_basis: Optional[int] = None,
    ) -> bool:
        # One merged submission for the whole batch (the applier commits
        # the node-disjoint subset as a single raft apply + bulk store
        # transaction, serial-fallback for conflicting plans). Returns
        # whether EVERY plan committed in full — a trimmed plan means the
        # chained used' tensor carries placements that never landed.
        # blocked_basis — for a CHAINED solve, the parent's snapshot
        # index: blocked evals must not mark capacity events between the
        # chain basis and this snapshot as already seen.
        submit = [
            (ev, plans[ev.id]) for ev in evals if not plans[ev.id].is_no_op()
        ]
        results: dict[str, PlanResult] = {}
        if submit:
            got = self.planner.submit_plan_batch([p for _, p in submit])
            results = {ev.id: r for (ev, _), r in zip(submit, got)}
        all_full = True
        updates: list[Evaluation] = []
        for ev in evals:
            plan = plans[ev.id]
            failed = dict(ev.failed_tg_allocs)
            blocked: Optional[Evaluation] = None
            result = results.get(ev.id)
            if result is not None:
                full, _, _ = result.full_commit(plan)
                if not full:
                    all_full = False
                    # partial commit: requeue the eval for a fresh pass
                    retry = ev.copy()
                    retry.status = "pending"
                    retry.snapshot_index = result.refresh_index
                    self.planner.create_eval(retry)
                    continue
            if failed:
                blocked = ev.create_blocked_eval({}, True, "", failed)
                blocked.snapshot_index = (
                    blocked_basis
                    if blocked_basis is not None
                    else snapshot.index
                )
                blocked.status_description = "created to place remaining allocations"
                self.planner.create_eval(blocked)
            done = ev.copy()
            done.status = "complete"
            done.failed_tg_allocs = failed
            if blocked is not None:
                done.blocked_eval = blocked.id
            updates.append(done)
        if updates:
            self.server.raft_apply("eval_update", updates)
        return all_full
