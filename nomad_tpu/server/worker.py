"""Scheduler workers: dequeue evals, invoke a scheduler, submit plans.

Reference: nomad/worker.go — run :105, dequeueEvaluation :142,
snapshotMinIndex :228, invokeScheduler :244, SubmitPlan :277 (the Planner
implementation backed by the plan queue).

Two worker flavors:
  * Worker — the reference-shaped loop: one eval at a time through the
    scheduler factory (host or TPU backend per SchedulerConfig).
  * TPUBatchWorker — drains many ready evals and solves them in ONE tensor
    batch (scheduler/tpu solve_eval_batch), submitting one plan per eval.
    This is what the ≥20x throughput target rides on: the broker's per-job
    serialization still holds (each dequeued eval is a different job).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from .. import metrics
from ..scheduler import new_scheduler
from ..scheduler.context import SchedulerConfig
from ..structs import Evaluation, Plan, PlanResult

logger = logging.getLogger("nomad_tpu.worker")

DEQUEUE_TIMEOUT_S = 0.5


class WorkerPlanner:
    """Planner interface backed by the server's plan queue + raft apply."""

    def __init__(self, server) -> None:
        self.server = server

    def submit_plan(self, plan: Plan):
        fut = self.server.plan_queue.enqueue(plan)
        result: PlanResult = fut.result(timeout=30)
        new_state = None
        if result.refresh_index > 0:
            new_state = self.server.state.snapshot_min_index(
                result.refresh_index, timeout_s=5
            )
        return result, new_state

    def update_eval(self, eval_obj: Evaluation) -> None:
        self.server.raft_apply("eval_update", [eval_obj])

    def create_eval(self, eval_obj: Evaluation) -> None:
        self.server.raft_apply("eval_update", [eval_obj])

    def refresh_state(self, min_index: int):
        return self.server.state.snapshot_min_index(min_index, timeout_s=5)


class Worker:
    def __init__(
        self,
        server,
        schedulers: list[str],
        config: Optional[SchedulerConfig] = None,
        name: str = "worker",
    ) -> None:
        self.server = server
        self.schedulers = schedulers
        self.config = config or SchedulerConfig()
        self.name = name
        self.planner = WorkerPlanner(server)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.processed = 0

    def start(self) -> None:
        # Fresh Event per incarnation: a thread that outlives join(timeout)
        # (e.g. blocked in submit_plan) polls ITS event and still exits,
        # instead of seeing a cleared shared flag and resuming as a twin.
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(self._stop,), daemon=True, name=self.name
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: float = 2.0) -> None:
        if self._thread:
            self._thread.join(timeout)

    def _run(self, stop: threading.Event) -> None:
        broker = self.server.eval_broker
        while not stop.is_set():
            ev, token = broker.dequeue(self.schedulers, timeout_s=DEQUEUE_TIMEOUT_S)
            if ev is None:
                continue
            t0 = time.perf_counter()
            try:
                self._process(ev)
            except Exception:
                logger.exception("%s: eval %s failed", self.name, ev.id)
                metrics.incr("nomad.worker.invoke.failed")
                try:
                    broker.nack(ev.id, token)
                except ValueError:
                    pass
                continue
            # reference telemetry: nomad.worker.invoke_scheduler.<type>
            metrics.observe(
                f"nomad.worker.invoke_seconds.{ev.type}",
                time.perf_counter() - t0,
            )
            try:
                broker.ack(ev.id, token)
            except ValueError:
                pass
            self.processed += 1

    def _process(self, ev: Evaluation) -> None:
        # Wait until our snapshot has caught up to the eval's creation
        # (reference: worker.go:121 snapshotMinIndex).
        wait_index = max(ev.modify_index, ev.snapshot_index)
        snapshot = self.server.state.snapshot_min_index(wait_index, timeout_s=5)
        if ev.type == "_core":
            # GC evals dispatch to the CoreScheduler, which mutates state
            # through the server's raft rather than submitting plans
            # (reference worker.go invokeScheduler: eval.Type == "_core").
            from .core_sched import CoreScheduler

            CoreScheduler(self.server, snapshot).process(ev)
            # Core evals are broker-only, never persisted (reference
            # leader.go schedulePeriodic enqueues without Raft) — acking
            # is all the cleanup they need.
            return
        sched = new_scheduler(ev.type, logger, snapshot, self.planner, self.config)
        sched.process(ev)


class TPUBatchWorker:
    """Drains up to `batch_size` ready evals per cycle and solves them in
    one batched tensor program."""

    def __init__(
        self,
        server,
        schedulers: list[str] = ("service", "batch"),
        batch_size: int = 64,
        config: Optional[SchedulerConfig] = None,
    ) -> None:
        self.server = server
        self.schedulers = list(schedulers)
        self.batch_size = batch_size
        self.config = config or SchedulerConfig(backend="tpu")
        self.planner = WorkerPlanner(server)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.processed = 0

    def start(self) -> None:
        # Fresh Event per incarnation (see Worker.start).
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(self._stop,), daemon=True,
            name="tpu-batch-worker"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self, stop: threading.Event) -> None:
        broker = self.server.eval_broker
        while not stop.is_set():
            batch: list[tuple[Evaluation, str]] = []
            ev, token = broker.dequeue(self.schedulers, timeout_s=DEQUEUE_TIMEOUT_S)
            if ev is None:
                continue
            batch.append((ev, token))
            # opportunistically drain more ready evals without waiting
            while len(batch) < self.batch_size:
                ev2, token2 = broker.dequeue(self.schedulers, timeout_s=0.01)
                if ev2 is None:
                    break
                batch.append((ev2, token2))
            try:
                self._process_batch([e for e, _ in batch])
            except Exception:
                logger.exception("tpu batch of %d failed", len(batch))
                for ev_, tok in batch:
                    try:
                        broker.nack(ev_.id, tok)
                    except ValueError:
                        pass
                continue
            for ev_, tok in batch:
                try:
                    broker.ack(ev_.id, tok)
                except ValueError:
                    pass
            self.processed += len(batch)

    def _process_batch(self, evals: list[Evaluation]) -> None:
        from ..scheduler.tpu import solve_eval_batch

        wait_index = max(
            max(ev.modify_index for ev in evals),
            max(ev.snapshot_index for ev in evals),
        )
        snapshot = self.server.state.snapshot_min_index(wait_index, timeout_s=5)
        t0 = time.perf_counter()
        plans = solve_eval_batch(snapshot, self.planner, evals, self.config)
        metrics.observe("nomad.tpu.batch_evals", len(evals))
        metrics.observe(
            "nomad.tpu.batch_solve_seconds", time.perf_counter() - t0
        )
        updates: list[Evaluation] = []
        for ev in evals:
            plan = plans[ev.id]
            failed = dict(ev.failed_tg_allocs)
            blocked: Optional[Evaluation] = None
            if not plan.is_no_op():
                result, new_state = self.planner.submit_plan(plan)
                full, _, _ = result.full_commit(plan)
                if not full:
                    # partial commit: requeue the eval for a fresh pass
                    retry = ev.copy()
                    retry.status = "pending"
                    retry.snapshot_index = result.refresh_index
                    self.planner.create_eval(retry)
                    continue
            if failed:
                blocked = ev.create_blocked_eval({}, True, "", failed)
                blocked.snapshot_index = snapshot.index
                blocked.status_description = "created to place remaining allocations"
                self.planner.create_eval(blocked)
            done = ev.copy()
            done.status = "complete"
            done.failed_tg_allocs = failed
            if blocked is not None:
                done.blocked_eval = blocked.id
            updates.append(done)
        if updates:
            self.server.raft_apply("eval_update", updates)
