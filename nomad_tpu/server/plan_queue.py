"""Plan queue: priority-ordered pending plans awaiting serial application.

Reference: nomad/plan_queue.go — Enqueue :95 returns a future the scheduler
worker blocks on; the plan applier dequeues in priority order.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from concurrent.futures import Future
from typing import Optional

from ..structs import Plan


class PlanQueue:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._heap: list = []
        self._counter = itertools.count()
        self._enabled = False

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            was = self._enabled
            self._enabled = enabled
            if was and not enabled:
                for _, _, _, fut in self._heap:
                    fut.cancel()
                self._heap.clear()
            self._cv.notify_all()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enqueue(self, plan: Plan) -> Future:
        fut: Future = Future()
        with self._lock:
            if not self._enabled:
                fut.set_exception(RuntimeError("plan queue is disabled"))
                return fut
            heapq.heappush(
                self._heap, (-plan.priority, next(self._counter), plan, fut)
            )
            self._cv.notify_all()
        return fut

    def dequeue(self, timeout_s: Optional[float] = None) -> Optional[tuple[Plan, Future]]:
        with self._cv:
            while True:
                if self._heap:
                    _, _, plan, fut = heapq.heappop(self._heap)
                    return plan, fut
                if not self._cv.wait(timeout_s if timeout_s is not None else 1.0):
                    if timeout_s is not None:
                        return None

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)
