"""Plan queue: priority-ordered pending plans awaiting serial application.

Reference: nomad/plan_queue.go — Enqueue :95 returns a future the scheduler
worker blocks on; the plan applier dequeues in priority order.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from concurrent.futures import Future
from typing import Optional

from .. import metrics
from ..structs import Plan


class PlanQueue:
    def __init__(self) -> None:
        # Lock-wait-attributed (hostobs.TimedLock): the solve-stage
        # enqueue and the applier's dequeue meet here; sustained waits
        # show up in /v1/profile/status locks and the lock_wait
        # histogram (docs/profiling.md).
        from ..hostobs import TimedLock

        self._lock = TimedLock("plan_queue", threading.Lock())
        self._cv = threading.Condition(self._lock)
        self._heap: list = []
        self._counter = itertools.count()
        self._enabled = False

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            was = self._enabled
            self._enabled = enabled
            if was and not enabled:
                for _, _, _, fut, _tctx, _t_enq in self._heap:
                    if isinstance(fut, list):
                        for f in fut:
                            f.cancel()
                    else:
                        fut.cancel()
                self._heap.clear()
            self._cv.notify_all()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enqueue(self, plan: Plan, trace_ctx=None) -> Future:
        """trace_ctx — optional (TraceContext, parent Span) the applier
        records its verify/apply spans under (trace.py)."""
        fut: Future = Future()
        with self._lock:
            if not self._enabled:
                fut.set_exception(RuntimeError("plan queue is disabled"))
                return fut
            heapq.heappush(
                self._heap,
                (-plan.priority, next(self._counter), plan, fut, trace_ctx,
                 time.monotonic()),
            )
            self._cv.notify_all()
        return fut

    def enqueue_batch(self, plans: list[Plan], trace_ctx=None) -> list[Future]:
        """Enqueue N same-snapshot plans as ONE queue item so the applier
        can verify/commit them together (merged plan apply). One future
        per plan; the heap entry rides at the batch's max priority. The
        applier's dequeue sees (list[Plan], list[Future]) and routes to
        its batch path."""
        futs: list[Future] = [Future() for _ in plans]
        if not plans:
            return futs
        with self._lock:
            if not self._enabled:
                for fut in futs:
                    fut.set_exception(RuntimeError("plan queue is disabled"))
                return futs
            prio = max(p.priority for p in plans)
            heapq.heappush(
                self._heap,
                (-prio, next(self._counter), list(plans), futs, trace_ctx,
                 time.monotonic()),
            )
            self._cv.notify_all()
        return futs

    def dequeue(
        self, timeout_s: Optional[float] = None
    ) -> Optional[tuple]:
        """Pop the highest-priority item as (plan, fut, trace_ctx). A
        single enqueue() yields (Plan, Future, _); an enqueue_batch()
        item yields parallel (list[Plan], list[Future], _) — consumers
        must branch on isinstance(plan, list) (the PlanApplier's run
        loop does)."""
        with self._cv:
            while True:
                if self._heap:
                    _, _, plan, fut, tctx, t_enq = heapq.heappop(self._heap)
                    break
                if not self._cv.wait(timeout_s if timeout_s is not None else 1.0):
                    if timeout_s is not None:
                        return None
        # observed OUTSIDE the queue lock (registry has its own lock):
        # how long the plan (or batch) sat queued before the applier
        # picked it up — the applier-backlog half of plan latency
        metrics.observe(
            "nomad.plan_queue.wait_seconds", time.monotonic() - t_enq
        )
        return plan, fut, tctx

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)
