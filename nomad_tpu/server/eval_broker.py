"""Evaluation broker: leader-only priority queue of pending evaluations.

Reference: nomad/eval_broker.go (901 LoC) — Enqueue :181, Dequeue :329,
Ack :531, Nack :595, delayed-eval heap :751, PendingEvaluations :861.

Semantics preserved:
  * per-scheduler-type priority heaps (workers dequeue only the types they
    run; the TPU batch worker dequeues many at once);
  * per-job serialization — at most ONE eval per (namespace, job) in flight;
    later evals for the same job wait in a per-job heap and are promoted on
    ack of the previous one;
  * ack/nack with a delivery limit: nacked evals re-enqueue after a delay,
    over-limit evals land in the failed queue;
  * delayed evals (wait_until in the future) sit in a time heap serviced by
    a timer thread.

Admission control (overload protection — the reference broker is
unbounded and relies on endpoint limits alone; a batched TPU solver
makes a bounded backlog mandatory because one mega-batch stall backs up
the whole pipeline):
  * ``admission_depth`` bounds the PENDING population (ready + per-job
    waiters + delayed; unacked in-flight evals are excluded). Past the
    depth an arriving eval is admitted only by displacing something:
    first an older duplicate waiting behind the same job (newest eval
    carries the freshest trigger — the state store cancels older
    pending evals on upsert the same way), else the lowest-priority
    pending eval strictly below the newcomer's priority. Otherwise the
    newcomer itself is shed.
  * ``namespace_cap`` is a per-namespace fairness bound: one namespace
    cannot occupy more than this many pending slots no matter how far
    below admission_depth the broker sits.
  * Every shed increments ``nomad.broker.shed`` (+ a per-reason
    counter) and finishes the eval's trace as "shed". A shed eval's
    state-store record stays pending: the next leadership restore or a
    superseding eval for the same job re-covers the work — shedding
    sheds BROKER load, never acked writes.

Shedding engages only when the knobs are set (depth 0 = unbounded, the
seed default), so an unconfigured broker behaves exactly as before.
Redeliveries (nack → delay → requeue) bypass admission: an eval that
was admitted once is never rejected at the door and never chosen as a
priority-displacement victim (it carries a live attempt count, which
keeps it out of the pending index). The one way a redelivery can still
leave early is DUPLICATE displacement — a newer eval for the same job
superseding it — which is safe by the same argument as the state
store's cancel-on-upsert: the newest eval re-covers the job's work.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Optional

from .. import blackbox, metrics, trace
from ..structs import Evaluation, generate_uuid, now_ns

DEFAULT_NACK_DELAY_S = 5.0
DEFAULT_DELIVERY_LIMIT = 3
FAILED_QUEUE = "_failed"


# Shared free-list cap for pooled 3-slot heap/unacked entries. At
# steady state every enqueue->dequeue->ack cycle recycles its entry
# instead of minting a tuple per hop; the cap bounds the pool after a
# backlog drains.
_ENTRY_POOL_CAP = 4096


class _PendingHeap:
    """Priority heap: higher priority first, then FIFO. ``dropped`` is
    the broker's shared tombstone set (admission-control evictions):
    entries whose eval id is in it are discarded lazily at pop/peek —
    heap surgery without O(n) re-heapify on the enqueue hot path.

    Entries are POOLED 3-slot lists ([-priority, seq, eval]) drawn from
    the broker's shared free list (``pool``): lists compare elementwise
    exactly like the tuples they replace, and recycling them at pop
    kills the per-eval entry allocation on the enqueue->dequeue path."""

    def __init__(
        self,
        dropped: Optional[set] = None,
        pool: Optional[list] = None,
    ) -> None:
        self._heap: list = []
        self._counter = itertools.count()
        self._dropped = dropped if dropped is not None else set()
        self._pool = pool if pool is not None else []

    def _entry(self, ev: Evaluation) -> list:
        pool = self._pool
        if pool:
            e = pool.pop()
            e[0] = -ev.priority
            e[1] = next(self._counter)
            e[2] = ev
            return e
        return [-ev.priority, next(self._counter), ev]

    def _recycle(self, entry: list) -> None:
        if len(self._pool) < _ENTRY_POOL_CAP:
            entry[2] = None
            self._pool.append(entry)

    def push(self, ev: Evaluation) -> None:
        heapq.heappush(self._heap, self._entry(ev))

    def push_all(self, evs: list) -> None:
        """Bulk admission: append pooled entries for the whole batch and
        heapify ONCE (O(n)) instead of sifting per push — the
        enqueue_all fast path."""
        heap = self._heap
        for ev in evs:
            heap.append(self._entry(ev))
        if len(heap) > 1:
            heapq.heapify(heap)

    def pop(self) -> Optional[Evaluation]:
        while self._heap:
            entry = heapq.heappop(self._heap)
            ev = entry[2]
            self._recycle(entry)
            if ev.id in self._dropped:
                self._dropped.discard(ev.id)
                continue
            return ev
        return None

    def peek(self) -> Optional[Evaluation]:
        while self._heap:
            ev = self._heap[0][2]
            if ev.id not in self._dropped:
                return ev
            self._recycle(heapq.heappop(self._heap))
            self._dropped.discard(ev.id)
        return None

    def oldest_waiter_below(self, priority: int) -> Optional[Evaluation]:
        """The oldest (smallest seq) live entry with priority <= the
        given one — the duplicate-shed victim. O(n) over this JOB's
        waiters only (bounded by per-job churn, not queue depth)."""
        best = None
        for _negp, seq, ev in self._heap:
            if ev.id in self._dropped or ev.priority > priority:
                continue
            if best is None or seq < best[0]:
                best = (seq, ev)
        return best[1] if best else None

    def __len__(self) -> int:
        return len(self._heap)


class EvalBroker:
    def __init__(
        self,
        nack_delay_s: float = DEFAULT_NACK_DELAY_S,
        delivery_limit: int = DEFAULT_DELIVERY_LIMIT,
        admission_depth: int = 0,
        namespace_cap: int = 0,
    ) -> None:
        self.nack_delay_s = nack_delay_s
        self.delivery_limit = delivery_limit
        # Admission knobs (0 = unbounded): see the module docstring.
        self.admission_depth = admission_depth
        self.namespace_cap = namespace_cap
        # Lock-wait-attributed (hostobs.TimedLock): every enqueue/
        # dequeue/ack/nack from every worker serializes here — the lock
        # the "GC-bound vs lock-bound vs materialize-bound" runbook
        # triage reads first (docs/operations.md). Uncontended cost is
        # one extra non-blocking try-acquire.
        from ..hostobs import TimedLock

        self._lock = TimedLock("broker", threading.RLock())
        self._cv = threading.Condition(self._lock)
        self._enabled = False
        # Tombstones for admission-control evictions: ids whose heap
        # entries are discarded lazily at the pop sites (ready heaps,
        # per-job waiter heaps, the delayed list).
        self._dropped: set[str] = set()
        # Pending-population index: eval id -> the broker's Evaluation
        # copy, for every PENDING eval (ready / waiting behind its job /
        # delayed; NOT unacked). The admission depth bounds len() of
        # this dict; the priority buckets make the lowest-priority
        # victim an O(priority-range) lookup instead of an O(depth)
        # scan, and holding the full eval lets a shed victim release
        # its job's in-flight slot correctly.
        self._pending_info: dict[str, Evaluation] = {}
        self._ns_pending: dict[str, int] = {}
        # priority -> insertion-ordered {eval_id: None} (FIFO within a
        # priority level, so the victim is the OLDEST at the lowest
        # priority)
        self._prio_buckets: dict[int, dict[str, None]] = {}
        self.shed_total = 0
        # Shared free list of pooled 3-slot entries, recycled across
        # every ready/waiter heap AND the unacked records: the
        # enqueue->dequeue->ack cycle reuses one list instead of
        # allocating a heap tuple at enqueue plus an unacked tuple at
        # dequeue per eval.
        self._entry_pool: list = []
        # scheduler type -> ready heap
        self._ready: dict[str, _PendingHeap] = {}
        # eval id -> [eval, token, attempts] for unacked evals (pooled
        # 3-slot lists from _entry_pool, returned at ack/nack)
        self._unacked: dict[str, list] = {}
        # (ns, job) -> in-flight eval id
        self._in_flight: dict[tuple[str, str], str] = {}
        # (ns, job) -> heap of evals waiting behind the in-flight one
        self._blocked_jobs: dict[tuple[str, str], _PendingHeap] = {}
        # delayed evals: (wait_until_ns, seq, eval)
        self._delayed: list = []
        self._delayed_counter = itertools.count()
        self._attempts: dict[str, int] = {}  # eval id -> deliveries
        # eval id -> (TraceContext, open Span) — the per-eval lifecycle
        # trace started at enqueue (trace.py). Bounded by queue depth:
        # entries leave at ack / dead-letter / flush.
        self._traces: dict[str, tuple] = {}
        # eval id -> monotonic FIRST-enqueue time: the basis of
        # nomad.eval.e2e_seconds, observed at ack (the worker acks only
        # after the plan is applied). setdefault keeps the original
        # enqueue across nack redeliveries so redelivered evals report
        # their true end-to-end time. Bounded like _traces: entries
        # leave at ack / dead-letter / flush.
        self._enqueue_times: dict[str, float] = {}
        # eval id -> monotonic time it last became READY (pushed onto a
        # ready heap): the basis of nomad.broker.wait_seconds at
        # dequeue. Distinct from _enqueue_times on purpose — a
        # redelivered eval's queue wait must not include the prior
        # attempt's processing time or the nack delay.
        self._wait_starts: dict[str, float] = {}
        self._timer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.stats = {
            "total_ready": 0,
            "total_unacked": 0,
            "total_blocked": 0,
            "total_waiting": 0,
            "failed": 0,
        }

    # -- configuration --------------------------------------------------

    def configure(
        self,
        nack_delay_s: Optional[float] = None,
        delivery_limit: Optional[int] = None,
        admission_depth: Optional[int] = None,
        namespace_cap: Optional[int] = None,
    ) -> None:
        """Live reconfiguration (agent SIGHUP reload): every knob applies
        to the running broker without a flush — in-flight deliveries
        keep their attempt counts, pending evals stay queued."""
        with self._lock:
            if nack_delay_s is not None:
                self.nack_delay_s = float(nack_delay_s)
            if delivery_limit is not None:
                self.delivery_limit = int(delivery_limit)
            if admission_depth is not None:
                self.admission_depth = int(admission_depth)
            if namespace_cap is not None:
                self.namespace_cap = int(namespace_cap)

    # -- lifecycle -----------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            was = self._enabled
            self._enabled = enabled
            if was and not enabled:
                self._flush_locked()
            if not was and enabled:
                self._stop.clear()
                self._timer = threading.Thread(
                    target=self._delayed_loop, daemon=True, name="broker-delayed"
                )
                self._timer.start()
            self._cv.notify_all()
        if was and not enabled:
            self._stop.set()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def _flush_locked(self) -> None:
        self._ready.clear()
        self._unacked.clear()
        self._in_flight.clear()
        self._blocked_jobs.clear()
        self._delayed.clear()
        # _attempts SURVIVES the flush on purpose: leadership often
        # bounces straight back to this node (restart churn), and a
        # redelivered eval must keep its delivery count or the
        # delivery_limit resets on every churn — a poison eval could
        # then loop forever instead of dead-lettering. Entries still
        # clear at ack/dead-letter; the cap guards pathological churn
        # where evals are acked on OTHER nodes and never clear here.
        # The eviction keeps counts for ids the broker still TRACKS
        # (_enqueue_times, cleared below, is exactly that set at this
        # point): a blanket clear() zeroed live in-flight evals'
        # delivery counts too, letting a poison eval dodge the
        # delivery_limit across every leadership bounce.
        if len(self._attempts) > 8192:
            tracked = self._enqueue_times
            self._attempts = {
                k: v for k, v in self._attempts.items() if k in tracked
            }
        # leadership loss: in-flight traces are abandoned, not recorded
        self._traces.clear()
        self._enqueue_times.clear()
        self._wait_starts.clear()
        self._dropped.clear()
        self._pending_info.clear()
        self._ns_pending.clear()
        self._prio_buckets.clear()

    # -- enqueue -------------------------------------------------------

    def enqueue(self, ev: Evaluation) -> None:
        with self._lock:
            self._enqueue_locked(ev.copy())

    def enqueue_all(self, evals: list[Evaluation]) -> None:
        """Batch enqueue: one lock acquisition for the whole batch, one
        timestamp read, one condition broadcast, and bulk per-type heap
        admission (append + single heapify) instead of a per-eval
        sift — the TPU batch producer's hot path. Admission control,
        per-job serialization, delayed evals, and traces run the exact
        per-eval logic `enqueue` does; only the ready-heap insertion
        and the wakeup are batched."""
        if not evals:
            return
        with self._lock:
            if not self._enabled:
                return
            bulk: dict[str, list] = {}
            now_mono = time.monotonic()
            for ev in evals:
                self._enqueue_locked(ev.copy(), bulk=bulk, now_mono=now_mono)
            for stype, ready in bulk.items():
                self._ready.setdefault(stype, self._heap()).push_all(ready)
            if bulk:
                self._cv.notify_all()

    # -- admission accounting -------------------------------------------

    def _pending_add(self, ev: Evaluation) -> None:
        if ev.id in self._pending_info:
            return
        if self._attempts.get(ev.id):
            # A redelivery (delivered at least once, nacked, waiting or
            # re-promoted): it was admitted when it first arrived, so it
            # neither counts against the admission depth nor enters the
            # displacement victim pool. Shedding a mid-retry eval would
            # break its e2e accounting and — worse, in the delay heap —
            # strand the job's queued waiters: its in-flight marker was
            # already cleared at nack, so _shed_locked would have no
            # slot to release and nothing would ever promote them.
            return
        self._pending_info[ev.id] = ev
        self._ns_pending[ev.namespace] = (
            self._ns_pending.get(ev.namespace, 0) + 1
        )
        self._prio_buckets.setdefault(ev.priority, {})[ev.id] = None

    def _pending_remove(self, eval_id: str) -> None:
        ev = self._pending_info.pop(eval_id, None)
        if ev is None:
            return
        n = self._ns_pending.get(ev.namespace, 0) - 1
        if n > 0:
            self._ns_pending[ev.namespace] = n
        else:
            self._ns_pending.pop(ev.namespace, None)
        bucket = self._prio_buckets.get(ev.priority)
        if bucket is not None:
            bucket.pop(eval_id, None)
            if not bucket:
                del self._prio_buckets[ev.priority]

    def _shed_locked(self, ev: Evaluation, reason: str,
                     tracked: bool) -> None:
        """Drop one eval from the broker's books. ``tracked`` — it was
        admitted earlier (an evicted victim) vs an arriving eval that
        never entered."""
        self.shed_total += 1
        metrics.incr("nomad.broker.shed")
        metrics.incr(f"nomad.broker.shed.{reason}")
        blackbox.record(
            blackbox.KIND_SHED, f"eval:{ev.id}", reason=reason,
            tracked=tracked,
            rel=[f"eval:{ev.id}"] + (
                [f"job:{ev.job_id}"] if ev.job_id else []
            ),
        )
        if tracked:
            self._dropped.add(ev.id)
            self._pending_remove(ev.id)
            self._wait_starts.pop(ev.id, None)
            # a shed eval is no longer the job's in-flight marker: a
            # READY victim held the slot — promote the next waiter so
            # the job never strands behind a tombstone
            key = (ev.namespace, ev.job_id)
            if ev.job_id and self._in_flight.get(key) == ev.id:
                self._release_job_locked(ev, ev.id)
        self._enqueue_times.pop(ev.id, None)
        tentry = self._traces.pop(ev.id, None)
        if tentry is not None:
            ctx, open_span = tentry
            open_span.attrs = dict(
                open_span.attrs or {}, outcome="shed", reason=reason
            )
            ctx.end_span(open_span)
            ctx.finish("shed")

    def _victim_below_locked(self, priority: int) -> Optional[Evaluation]:
        """Oldest pending eval at the lowest priority strictly below
        the given one (None when nothing qualifies)."""
        for prio in sorted(self._prio_buckets):
            if prio >= priority:
                return None
            bucket = self._prio_buckets[prio]
            if bucket:
                return self._pending_info[next(iter(bucket))]
        return None

    def _admit_locked(self, ev: Evaluation) -> bool:
        """Admission decision for a NEW enqueue. True = admitted (a
        duplicate or lower-priority victim may have been evicted to
        make room); False = shed the arrival."""
        if self.admission_depth <= 0 and self.namespace_cap <= 0:
            return True
        if ev.type == "_core" or ev.id in self._enqueue_times:
            # GC/core evals are leader-internal and tiny; a re-enqueue
            # of an id the broker already tracks (pending OR unacked)
            # must not double-count or shed the live eval's bookkeeping
            return True
        pending = len(self._pending_info)
        ns_full = (
            self.namespace_cap > 0
            and self._ns_pending.get(ev.namespace, 0) >= self.namespace_cap
        )
        depth_full = (
            self.admission_depth > 0 and pending >= self.admission_depth
        )
        if not ns_full and not depth_full:
            return True
        # 1) duplicate displacement: the job already has waiters — the
        # oldest duplicate at <= priority yields its slot to the newest
        # trigger (works for both the depth and the namespace bound,
        # since the duplicate shares the namespace)
        key = (ev.namespace, ev.job_id)
        waiters = self._blocked_jobs.get(key) if ev.job_id else None
        if waiters is not None:
            dup = waiters.oldest_waiter_below(ev.priority)
            if dup is not None:
                self._shed_locked(dup, "duplicate", tracked=True)
                return True
        if ns_full:
            # fairness cap: no cross-namespace eviction — the newcomer's
            # own namespace is over budget, so it is the one shed
            self._shed_locked(ev, "namespace", tracked=False)
            return False
        # 2) priority displacement: evict the oldest lowest-priority
        # pending eval strictly below the newcomer. The victim may be
        # READY and holding its job's in-flight slot — _shed_locked
        # releases it and promotes the next waiter, so the job never
        # strands behind a tombstone.
        victim = self._victim_below_locked(ev.priority)
        if victim is not None:
            self._shed_locked(victim, "depth", tracked=True)
            return True
        self._shed_locked(ev, "depth", tracked=False)
        return False

    def _enqueue_locked(
        self,
        ev: Evaluation,
        bulk: Optional[dict] = None,
        now_mono: Optional[float] = None,
    ) -> None:
        if not self._enabled:
            return
        if not self._admit_locked(ev):
            return
        if now_mono is None:
            now_mono = time.monotonic()
        self._enqueue_times.setdefault(ev.id, now_mono)
        if trace.enabled() and ev.id not in self._traces:
            ctx = trace.start_trace(
                "eval",
                eval_id=ev.id,
                job_id=ev.job_id,
                type=ev.type,
                triggered_by=ev.triggered_by,
            )
            if ctx is not None:
                self._traces[ev.id] = (
                    ctx,
                    ctx.start_span("broker.wait", detached=True),
                )
        if ev.wait_until_ns and ev.wait_until_ns > now_ns():
            self._pending_add(ev)
            heapq.heappush(
                self._delayed, (ev.wait_until_ns, next(self._delayed_counter), ev)
            )
            self._cv.notify_all()
            return
        key = (ev.namespace, ev.job_id)
        if ev.job_id and key in self._in_flight:
            self._pending_add(ev)
            self._blocked_jobs.setdefault(key, self._heap()).push(ev)
            return
        self._push_ready(ev, bulk=bulk, now_mono=now_mono)

    def _heap(self) -> _PendingHeap:
        """A heap sharing the broker's admission tombstone set and
        pooled-entry free list."""
        return _PendingHeap(self._dropped, self._entry_pool)

    def _push_ready(
        self,
        ev: Evaluation,
        bulk: Optional[dict] = None,
        now_mono: Optional[float] = None,
    ) -> None:
        self._pending_add(ev)
        self._wait_starts[ev.id] = (
            now_mono if now_mono is not None else time.monotonic()
        )
        if ev.job_id:
            self._in_flight[(ev.namespace, ev.job_id)] = ev.id
        if bulk is not None:
            # enqueue_all collects per-type lists; the caller bulk-pushes
            # each heap once and broadcasts once after the loop
            bulk.setdefault(ev.type, []).append(ev)
            return
        self._ready.setdefault(ev.type, self._heap()).push(ev)
        self._cv.notify_all()

    # -- dequeue / ack / nack -----------------------------------------

    def dequeue(
        self, schedulers: list[str], timeout_s: Optional[float] = None
    ) -> tuple[Optional[Evaluation], str]:
        """Blocking dequeue of the highest-priority ready eval among the
        given scheduler types. Returns (eval, token) or (None, "")."""
        deadline = time.monotonic() + timeout_s if timeout_s is not None else None
        while True:
            wait_s = None
            with self._cv:
                if self._enabled:
                    ev = self._pop_best_locked(schedulers)
                    if ev is not None:
                        # pending -> in-flight: the admission bound
                        # covers the backlog, not work being processed
                        self._pending_remove(ev.id)
                        # per-dequeue token: generate_uuid serves from
                        # the bulk-minted pool (one generate_uuids(256)
                        # pass per 256 ids — no per-dequeue entropy
                        # syscall or format work)
                        token = generate_uuid()
                        attempts = self._attempts.get(ev.id, 0) + 1
                        self._attempts[ev.id] = attempts
                        # pooled unacked record: reuse a free 3-slot
                        # entry instead of minting a tuple per delivery
                        pool = self._entry_pool
                        rec = pool.pop() if pool else [None, None, None]
                        rec[0], rec[1], rec[2] = ev, token, attempts
                        self._unacked[ev.id] = rec
                        ready_at = self._wait_starts.pop(ev.id, None)
                        if ready_at is not None:
                            wait_s = time.monotonic() - ready_at
                        entry = self._traces.get(ev.id)
                        if entry is not None:
                            ctx, open_span = entry
                            ctx.end_span(open_span)
                            # NOT detached: dequeue runs on the worker's
                            # own thread, so the processing span rides
                            # that thread's stack and the worker's
                            # snapshot/scheduler/plan spans nest under it
                            self._traces[ev.id] = (
                                ctx,
                                ctx.start_span(
                                    "processing",
                                    parent=ctx.root,
                                    attempt=attempts,
                                ),
                            )
                        break
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None, ""
                    self._cv.wait(remaining)
                else:
                    self._cv.wait(1.0)
        # histogram observe OUTSIDE the broker lock: the registry has
        # its own lock and nesting it under _cv would add a lock-order
        # edge the racecheck battery would have to carry forever
        if wait_s is not None:
            metrics.observe("nomad.broker.wait_seconds", wait_s)
        return ev, token

    def _pop_best_locked(self, schedulers: list[str]) -> Optional[Evaluation]:
        best_type = None
        best = None
        for stype in schedulers:
            heap = self._ready.get(stype)
            if heap is None:
                continue
            ev = heap.peek()
            if ev is None:
                continue
            if best is None or ev.priority > best.priority:
                best, best_type = ev, stype
        if best is None:
            return None
        return self._ready[best_type].pop()

    def ack(self, eval_id: str, token: str) -> None:
        with self._lock:
            entry = self._unacked.get(eval_id)
            if entry is None or entry[1] != token:
                raise ValueError(f"token mismatch or unknown eval {eval_id}")
            del self._unacked[eval_id]
            ev = entry[0]
            if len(self._entry_pool) < _ENTRY_POOL_CAP:
                entry[0] = entry[1] = entry[2] = None
                self._entry_pool.append(entry)
            self._attempts.pop(eval_id, None)
            self._release_job_locked(ev, eval_id)
            tentry = self._traces.pop(eval_id, None)
            enq = self._enqueue_times.pop(eval_id, None)
        if enq is not None:
            # ack lands only after the eval's plan was applied (workers
            # ack post-commit), so this IS the end-to-end eval latency:
            # broker enqueue -> plan applied. One aggregate histogram
            # plus a per-(scheduler type, triggered-by) labelled one —
            # both label sets are small and closed.
            e2e = time.monotonic() - enq
            metrics.observe("nomad.eval.e2e_seconds", e2e)
            metrics.observe(
                f"nomad.eval.e2e_seconds.{ev.type}"
                f".{ev.triggered_by or 'unknown'}",
                e2e,
            )
        if tentry is not None:
            ctx, open_span = tentry
            ctx.end_span(open_span)
            ctx.finish("ok")

    def nack(self, eval_id: str, token: str) -> None:
        with self._lock:
            entry = self._unacked.get(eval_id)
            if entry is None or entry[1] != token:
                raise ValueError(f"token mismatch or unknown eval {eval_id}")
            del self._unacked[eval_id]
            ev, _, attempts = entry
            if len(self._entry_pool) < _ENTRY_POOL_CAP:
                entry[0] = entry[1] = entry[2] = None
                self._entry_pool.append(entry)
            key = (ev.namespace, ev.job_id)
            if attempts >= self.delivery_limit:
                # dead-letter: failed queue for the reaper; the job's waiting
                # evals must still be promoted or they strand forever
                self._attempts.pop(eval_id, None)
                self._release_job_locked(ev, eval_id)
                self._ready.setdefault(FAILED_QUEUE, self._heap()).push(ev)
                self.stats["failed"] += 1
                self._cv.notify_all()
                self._enqueue_times.pop(eval_id, None)
                tentry = self._traces.pop(eval_id, None)
                if tentry is not None:
                    ctx, open_span = tentry
                    open_span.attrs = dict(open_span.attrs or {},
                                           outcome="nack")
                    ctx.end_span(open_span)
                    ctx.finish("failed")
                return
            if self._in_flight.get(key) == eval_id:
                del self._in_flight[key]
            tentry = self._traces.get(eval_id)
            if tentry is not None:
                ctx, open_span = tentry
                open_span.attrs = dict(open_span.attrs or {}, outcome="nack")
                ctx.end_span(open_span)
                self._traces[eval_id] = (
                    ctx,
                    ctx.start_span(
                        "nack.wait", parent=ctx.root, detached=True
                    ),
                )
            # re-enqueue after the nack delay. Redeliveries bypass
            # admission entirely — _pending_add refuses ids with a live
            # attempt count, so a retry is never rejected at the door
            # NOR chosen as a displacement victim while it waits.
            requeue_at = now_ns() + int(self.nack_delay_s * 1e9)
            heapq.heappush(
                self._delayed, (requeue_at, next(self._delayed_counter), ev)
            )
            self._cv.notify_all()

    def _release_job_locked(self, ev: Evaluation, eval_id: str) -> None:
        """Clear the job's in-flight marker and promote the next waiter."""
        key = (ev.namespace, ev.job_id)
        if self._in_flight.get(key) == eval_id:
            del self._in_flight[key]
        blocked = self._blocked_jobs.get(key)
        if blocked:
            nxt = blocked.pop()
            if len(blocked) == 0:
                del self._blocked_jobs[key]
            if nxt is not None:
                self._push_ready(nxt)

    # -- delayed servicing --------------------------------------------

    def _delayed_loop(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                now = now_ns()
                while self._delayed and self._delayed[0][0] <= now:
                    _, _, ev = heapq.heappop(self._delayed)
                    if ev.id in self._dropped:
                        # admission-control eviction landed while the
                        # eval sat in the delay heap
                        self._dropped.discard(ev.id)
                        continue
                    key = (ev.namespace, ev.job_id)
                    if ev.job_id and key in self._in_flight:
                        self._blocked_jobs.setdefault(key, self._heap()).push(ev)
                    else:
                        self._push_ready(ev)
                wait = 0.2
                if self._delayed:
                    wait = min(wait, max(0.0, (self._delayed[0][0] - now) / 1e9))
            self._stop.wait(wait)

    # -- introspection -------------------------------------------------

    def pending_count(self) -> int:
        """Admitted-but-undelivered evals (ready + per-job waiters +
        delayed) — the population admission_depth bounds."""
        with self._lock:
            return len(self._pending_info)

    def namespace_pending(self, namespace: str) -> int:
        with self._lock:
            return self._ns_pending.get(namespace, 0)

    def saturation(self, namespace: str = "") -> Optional[tuple[str, float]]:
        """Front-door admission probe: (reason, retry_after_s) when a
        new eval for this namespace would be rejected outright — the
        leader's eval-minting write endpoints call this BEFORE raft so
        overload surfaces as 429 instead of a shed after commit. None
        while there is room (or admission is unconfigured/disabled).

        Saturated means even displacement cannot help an average-
        priority arrival: pending >= depth with nothing obviously
        evictable is approximated as pending >= depth (the per-eval
        displacement still runs for internal producers; the front door
        is simply told to back off first — the reference's posture of
        rejecting at the edge before queueing in the core). The hint
        scales with how far past the bound the backlog sits."""
        with self._lock:
            if not self._enabled:
                return None
            if (
                self.namespace_cap > 0
                and namespace
                and self._ns_pending.get(namespace, 0) >= self.namespace_cap
            ):
                return ("namespace", self.nack_delay_s / 4)
            if self.admission_depth > 0:
                pending = len(self._pending_info)
                if pending >= self.admission_depth:
                    over = pending - self.admission_depth
                    return (
                        "depth",
                        min(5.0, 0.5 + over / max(1, self.admission_depth)),
                    )
        return None

    def stats_snapshot(self) -> dict:
        """Live queue depths + shed counters for the metrics provider.
        (The legacy ``stats`` dict only ever tracked dead-letters; these
        gauges are computed from the real structures under the lock so
        `operator top` shows true depths.)"""
        with self._lock:
            ready = sum(
                len(h) for t, h in self._ready.items() if t != FAILED_QUEUE
            )
            waiters = sum(len(h) for h in self._blocked_jobs.values())
            return {
                "total_ready": ready,
                "total_unacked": len(self._unacked),
                "total_blocked": waiters,
                "total_waiting": len(self._delayed),
                "total_pending": len(self._pending_info),
                "total_shed": self.shed_total,
                "admission_depth": self.admission_depth,
                "namespace_cap": self.namespace_cap,
                "failed": self.stats["failed"],
            }

    def tracks(self, eval_id: str) -> bool:
        """Is this eval currently anywhere in the broker (ready, unacked,
        waiting behind its job, or nack-delayed)? _enqueue_times is
        exactly that set: setdefault'ed on every enqueue, popped only at
        ack / dead-letter / flush. Used by the leader's _restore_evals
        so restoring state after churn is idempotent — an eval the FSM
        side-channel already enqueued is not enqueued again."""
        with self._lock:
            return eval_id in self._enqueue_times

    def trace_context(self, eval_id: str):
        """The in-flight eval's TraceContext (None when untracked): the
        worker installs it as the thread's current context so scheduler
        and plan spans land on the eval's own trace."""
        with self._lock:
            entry = self._traces.get(eval_id)
        return entry[0] if entry is not None else None

    def annotate_trace(self, eval_id: str, **attrs) -> None:
        """Attach attrs to an in-flight eval's trace (the TPU batch
        worker links each eval to its batch trace this way)."""
        with self._lock:
            entry = self._traces.get(eval_id)
        if entry is not None:
            for k, v in attrs.items():
                entry[0].set_attr(k, v)

    def ready_count(self) -> int:
        with self._lock:
            return sum(len(h) for t, h in self._ready.items() if t != FAILED_QUEUE)

    def inflight_count(self) -> int:
        with self._lock:
            return len(self._unacked)

    def outstanding(self, eval_id: str) -> bool:
        with self._lock:
            return eval_id in self._unacked
