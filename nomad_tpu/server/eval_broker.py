"""Evaluation broker: leader-only priority queue of pending evaluations.

Reference: nomad/eval_broker.go (901 LoC) — Enqueue :181, Dequeue :329,
Ack :531, Nack :595, delayed-eval heap :751, PendingEvaluations :861.

Semantics preserved:
  * per-scheduler-type priority heaps (workers dequeue only the types they
    run; the TPU batch worker dequeues many at once);
  * per-job serialization — at most ONE eval per (namespace, job) in flight;
    later evals for the same job wait in a per-job heap and are promoted on
    ack of the previous one;
  * ack/nack with a delivery limit: nacked evals re-enqueue after a delay,
    over-limit evals land in the failed queue;
  * delayed evals (wait_until in the future) sit in a time heap serviced by
    a timer thread.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Optional

from .. import metrics, trace
from ..structs import Evaluation, generate_uuid, now_ns

DEFAULT_NACK_DELAY_S = 5.0
DEFAULT_DELIVERY_LIMIT = 3
FAILED_QUEUE = "_failed"


class _PendingHeap:
    """Priority heap: higher priority first, then FIFO."""

    def __init__(self) -> None:
        self._heap: list = []
        self._counter = itertools.count()

    def push(self, ev: Evaluation) -> None:
        heapq.heappush(self._heap, (-ev.priority, next(self._counter), ev))

    def pop(self) -> Optional[Evaluation]:
        if not self._heap:
            return None
        return heapq.heappop(self._heap)[2]

    def peek(self) -> Optional[Evaluation]:
        return self._heap[0][2] if self._heap else None

    def __len__(self) -> int:
        return len(self._heap)


class EvalBroker:
    def __init__(
        self,
        nack_delay_s: float = DEFAULT_NACK_DELAY_S,
        delivery_limit: int = DEFAULT_DELIVERY_LIMIT,
    ) -> None:
        self.nack_delay_s = nack_delay_s
        self.delivery_limit = delivery_limit
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._enabled = False
        # scheduler type -> ready heap
        self._ready: dict[str, _PendingHeap] = {}
        # eval id -> (eval, token, attempts) for unacked evals
        self._unacked: dict[str, tuple[Evaluation, str, int]] = {}
        # (ns, job) -> in-flight eval id
        self._in_flight: dict[tuple[str, str], str] = {}
        # (ns, job) -> heap of evals waiting behind the in-flight one
        self._blocked_jobs: dict[tuple[str, str], _PendingHeap] = {}
        # delayed evals: (wait_until_ns, seq, eval)
        self._delayed: list = []
        self._delayed_counter = itertools.count()
        self._attempts: dict[str, int] = {}  # eval id -> deliveries
        # eval id -> (TraceContext, open Span) — the per-eval lifecycle
        # trace started at enqueue (trace.py). Bounded by queue depth:
        # entries leave at ack / dead-letter / flush.
        self._traces: dict[str, tuple] = {}
        # eval id -> monotonic FIRST-enqueue time: the basis of
        # nomad.eval.e2e_seconds, observed at ack (the worker acks only
        # after the plan is applied). setdefault keeps the original
        # enqueue across nack redeliveries so redelivered evals report
        # their true end-to-end time. Bounded like _traces: entries
        # leave at ack / dead-letter / flush.
        self._enqueue_times: dict[str, float] = {}
        # eval id -> monotonic time it last became READY (pushed onto a
        # ready heap): the basis of nomad.broker.wait_seconds at
        # dequeue. Distinct from _enqueue_times on purpose — a
        # redelivered eval's queue wait must not include the prior
        # attempt's processing time or the nack delay.
        self._wait_starts: dict[str, float] = {}
        self._timer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.stats = {
            "total_ready": 0,
            "total_unacked": 0,
            "total_blocked": 0,
            "total_waiting": 0,
            "failed": 0,
        }

    # -- lifecycle -----------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            was = self._enabled
            self._enabled = enabled
            if was and not enabled:
                self._flush_locked()
            if not was and enabled:
                self._stop.clear()
                self._timer = threading.Thread(
                    target=self._delayed_loop, daemon=True, name="broker-delayed"
                )
                self._timer.start()
            self._cv.notify_all()
        if was and not enabled:
            self._stop.set()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def _flush_locked(self) -> None:
        self._ready.clear()
        self._unacked.clear()
        self._in_flight.clear()
        self._blocked_jobs.clear()
        self._delayed.clear()
        # _attempts SURVIVES the flush on purpose: leadership often
        # bounces straight back to this node (restart churn), and a
        # redelivered eval must keep its delivery count or the
        # delivery_limit resets on every churn — a poison eval could
        # then loop forever instead of dead-lettering. Entries still
        # clear at ack/dead-letter; the cap guards pathological churn
        # where evals are acked on OTHER nodes and never clear here.
        if len(self._attempts) > 8192:
            self._attempts.clear()
        # leadership loss: in-flight traces are abandoned, not recorded
        self._traces.clear()
        self._enqueue_times.clear()
        self._wait_starts.clear()

    # -- enqueue -------------------------------------------------------

    def enqueue(self, ev: Evaluation) -> None:
        with self._lock:
            self._enqueue_locked(ev.copy())

    def enqueue_all(self, evals: list[Evaluation]) -> None:
        with self._lock:
            for ev in evals:
                self._enqueue_locked(ev.copy())

    def _enqueue_locked(self, ev: Evaluation) -> None:
        if not self._enabled:
            return
        self._enqueue_times.setdefault(ev.id, time.monotonic())
        if trace.enabled() and ev.id not in self._traces:
            ctx = trace.start_trace(
                "eval",
                eval_id=ev.id,
                job_id=ev.job_id,
                type=ev.type,
                triggered_by=ev.triggered_by,
            )
            if ctx is not None:
                self._traces[ev.id] = (
                    ctx,
                    ctx.start_span("broker.wait", detached=True),
                )
        if ev.wait_until_ns and ev.wait_until_ns > now_ns():
            heapq.heappush(
                self._delayed, (ev.wait_until_ns, next(self._delayed_counter), ev)
            )
            self._cv.notify_all()
            return
        key = (ev.namespace, ev.job_id)
        if ev.job_id and key in self._in_flight:
            self._blocked_jobs.setdefault(key, _PendingHeap()).push(ev)
            return
        self._push_ready(ev)

    def _push_ready(self, ev: Evaluation) -> None:
        self._ready.setdefault(ev.type, _PendingHeap()).push(ev)
        self._wait_starts[ev.id] = time.monotonic()
        if ev.job_id:
            self._in_flight[(ev.namespace, ev.job_id)] = ev.id
        self._cv.notify_all()

    # -- dequeue / ack / nack -----------------------------------------

    def dequeue(
        self, schedulers: list[str], timeout_s: Optional[float] = None
    ) -> tuple[Optional[Evaluation], str]:
        """Blocking dequeue of the highest-priority ready eval among the
        given scheduler types. Returns (eval, token) or (None, "")."""
        deadline = time.monotonic() + timeout_s if timeout_s is not None else None
        while True:
            wait_s = None
            with self._cv:
                if self._enabled:
                    ev = self._pop_best_locked(schedulers)
                    if ev is not None:
                        token = generate_uuid()
                        attempts = self._attempts.get(ev.id, 0) + 1
                        self._attempts[ev.id] = attempts
                        self._unacked[ev.id] = (ev, token, attempts)
                        ready_at = self._wait_starts.pop(ev.id, None)
                        if ready_at is not None:
                            wait_s = time.monotonic() - ready_at
                        entry = self._traces.get(ev.id)
                        if entry is not None:
                            ctx, open_span = entry
                            ctx.end_span(open_span)
                            # NOT detached: dequeue runs on the worker's
                            # own thread, so the processing span rides
                            # that thread's stack and the worker's
                            # snapshot/scheduler/plan spans nest under it
                            self._traces[ev.id] = (
                                ctx,
                                ctx.start_span(
                                    "processing",
                                    parent=ctx.root,
                                    attempt=attempts,
                                ),
                            )
                        break
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None, ""
                    self._cv.wait(remaining)
                else:
                    self._cv.wait(1.0)
        # histogram observe OUTSIDE the broker lock: the registry has
        # its own lock and nesting it under _cv would add a lock-order
        # edge the racecheck battery would have to carry forever
        if wait_s is not None:
            metrics.observe("nomad.broker.wait_seconds", wait_s)
        return ev, token

    def _pop_best_locked(self, schedulers: list[str]) -> Optional[Evaluation]:
        best_type = None
        best = None
        for stype in schedulers:
            heap = self._ready.get(stype)
            if heap is None:
                continue
            ev = heap.peek()
            if ev is None:
                continue
            if best is None or ev.priority > best.priority:
                best, best_type = ev, stype
        if best is None:
            return None
        return self._ready[best_type].pop()

    def ack(self, eval_id: str, token: str) -> None:
        with self._lock:
            entry = self._unacked.get(eval_id)
            if entry is None or entry[1] != token:
                raise ValueError(f"token mismatch or unknown eval {eval_id}")
            del self._unacked[eval_id]
            ev = entry[0]
            self._attempts.pop(eval_id, None)
            self._release_job_locked(ev, eval_id)
            tentry = self._traces.pop(eval_id, None)
            enq = self._enqueue_times.pop(eval_id, None)
        if enq is not None:
            # ack lands only after the eval's plan was applied (workers
            # ack post-commit), so this IS the end-to-end eval latency:
            # broker enqueue -> plan applied. One aggregate histogram
            # plus a per-(scheduler type, triggered-by) labelled one —
            # both label sets are small and closed.
            e2e = time.monotonic() - enq
            metrics.observe("nomad.eval.e2e_seconds", e2e)
            metrics.observe(
                f"nomad.eval.e2e_seconds.{ev.type}"
                f".{ev.triggered_by or 'unknown'}",
                e2e,
            )
        if tentry is not None:
            ctx, open_span = tentry
            ctx.end_span(open_span)
            ctx.finish("ok")

    def nack(self, eval_id: str, token: str) -> None:
        with self._lock:
            entry = self._unacked.get(eval_id)
            if entry is None or entry[1] != token:
                raise ValueError(f"token mismatch or unknown eval {eval_id}")
            del self._unacked[eval_id]
            ev, _, attempts = entry
            key = (ev.namespace, ev.job_id)
            if attempts >= self.delivery_limit:
                # dead-letter: failed queue for the reaper; the job's waiting
                # evals must still be promoted or they strand forever
                self._attempts.pop(eval_id, None)
                self._release_job_locked(ev, eval_id)
                self._ready.setdefault(FAILED_QUEUE, _PendingHeap()).push(ev)
                self.stats["failed"] += 1
                self._cv.notify_all()
                self._enqueue_times.pop(eval_id, None)
                tentry = self._traces.pop(eval_id, None)
                if tentry is not None:
                    ctx, open_span = tentry
                    open_span.attrs = dict(open_span.attrs or {},
                                           outcome="nack")
                    ctx.end_span(open_span)
                    ctx.finish("failed")
                return
            if self._in_flight.get(key) == eval_id:
                del self._in_flight[key]
            tentry = self._traces.get(eval_id)
            if tentry is not None:
                ctx, open_span = tentry
                open_span.attrs = dict(open_span.attrs or {}, outcome="nack")
                ctx.end_span(open_span)
                self._traces[eval_id] = (
                    ctx,
                    ctx.start_span(
                        "nack.wait", parent=ctx.root, detached=True
                    ),
                )
            # re-enqueue after the nack delay
            requeue_at = now_ns() + int(self.nack_delay_s * 1e9)
            heapq.heappush(
                self._delayed, (requeue_at, next(self._delayed_counter), ev)
            )
            self._cv.notify_all()

    def _release_job_locked(self, ev: Evaluation, eval_id: str) -> None:
        """Clear the job's in-flight marker and promote the next waiter."""
        key = (ev.namespace, ev.job_id)
        if self._in_flight.get(key) == eval_id:
            del self._in_flight[key]
        blocked = self._blocked_jobs.get(key)
        if blocked:
            nxt = blocked.pop()
            if len(blocked) == 0:
                del self._blocked_jobs[key]
            if nxt is not None:
                self._push_ready(nxt)

    # -- delayed servicing --------------------------------------------

    def _delayed_loop(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                now = now_ns()
                while self._delayed and self._delayed[0][0] <= now:
                    _, _, ev = heapq.heappop(self._delayed)
                    key = (ev.namespace, ev.job_id)
                    if ev.job_id and key in self._in_flight:
                        self._blocked_jobs.setdefault(key, _PendingHeap()).push(ev)
                    else:
                        self._push_ready(ev)
                wait = 0.2
                if self._delayed:
                    wait = min(wait, max(0.0, (self._delayed[0][0] - now) / 1e9))
            self._stop.wait(wait)

    # -- introspection -------------------------------------------------

    def tracks(self, eval_id: str) -> bool:
        """Is this eval currently anywhere in the broker (ready, unacked,
        waiting behind its job, or nack-delayed)? _enqueue_times is
        exactly that set: setdefault'ed on every enqueue, popped only at
        ack / dead-letter / flush. Used by the leader's _restore_evals
        so restoring state after churn is idempotent — an eval the FSM
        side-channel already enqueued is not enqueued again."""
        with self._lock:
            return eval_id in self._enqueue_times

    def trace_context(self, eval_id: str):
        """The in-flight eval's TraceContext (None when untracked): the
        worker installs it as the thread's current context so scheduler
        and plan spans land on the eval's own trace."""
        with self._lock:
            entry = self._traces.get(eval_id)
        return entry[0] if entry is not None else None

    def annotate_trace(self, eval_id: str, **attrs) -> None:
        """Attach attrs to an in-flight eval's trace (the TPU batch
        worker links each eval to its batch trace this way)."""
        with self._lock:
            entry = self._traces.get(eval_id)
        if entry is not None:
            for k, v in attrs.items():
                entry[0].set_attr(k, v)

    def ready_count(self) -> int:
        with self._lock:
            return sum(len(h) for t, h in self._ready.items() if t != FAILED_QUEUE)

    def inflight_count(self) -> int:
        with self._lock:
            return len(self._unacked)

    def outstanding(self, eval_id: str) -> bool:
        with self._lock:
            return eval_id in self._unacked
