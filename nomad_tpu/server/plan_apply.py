"""Plan applier: THE serialization point of the optimistic scheduler.

Reference: nomad/plan_apply.go — planApply :71, evaluatePlan :400,
evaluateNodePlan :631. Scheduler workers race against stale snapshots; the
applier re-verifies every touched node against the LATEST state and commits
only the subset that still fits. A partial commit sets refresh_index, which
forces the worker to refresh its snapshot and retry the remainder.

Reference parallelizes per-node verification over a pool
(plan_apply_pool.go) and pipelines verification of plan N+1 with the Raft
apply of plan N; under the GIL a thread pool buys nothing, so verification
here is a straight loop over touched nodes — the batched TPU path already
amortizes this by submitting fewer, larger plans.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from ..structs import Plan, PlanResult, allocs_fit
from ..structs.structs import NODE_STATUS_READY
from .plan_queue import PlanQueue

logger = logging.getLogger("nomad_tpu.plan_apply")


def evaluate_node_plan(snapshot, plan: Plan, node_id: str) -> tuple[bool, str]:
    """Would this plan's changes to one node fit? (reference :631)."""
    proposed = plan.node_allocation.get(node_id, [])
    if not proposed:
        return True, ""  # stops/preemptions alone always apply
    node = snapshot.node_by_id(node_id)
    if node is None:
        return False, "node does not exist"
    if node.status != NODE_STATUS_READY:
        return False, f"node is {node.status}"

    existing = snapshot.allocs_by_node_terminal(node_id, False)
    remove = {a.id for a in plan.node_update.get(node_id, [])}
    remove |= {a.id for a in plan.node_preemptions.get(node_id, [])}
    update_ids = {a.id for a in proposed}
    keep = [a for a in existing if a.id not in remove and a.id not in update_ids]
    fit, dim, _ = allocs_fit(node, keep + list(proposed))
    if not fit:
        return False, dim
    return True, ""


def _volume_overcommitted_nodes(snapshot, plan: Plan) -> set[str]:
    """Nodes whose placements would exceed a registered volume's write
    capacity, counting claims already committed AND earlier placements in
    this same plan (first-come order by node id for determinism)."""
    if not hasattr(snapshot, "volumes_by_name"):
        return set()
    # Claims held by allocs this plan stops/evicts/replaces don't count
    # against the new placements (same rule evaluate_node_plan applies to
    # resource fit): a destructive update of the single writer must not
    # conflict with its own predecessor.
    removed: set[str] = set()
    for allocs in plan.node_update.values():
        removed.update(a.id for a in allocs)
    for allocs in plan.node_preemptions.values():
        removed.update(a.id for a in allocs)
    for allocs in plan.node_allocation.values():
        removed.update(a.id for a in allocs)  # in-place updates of selves
    writers: dict[tuple[str, str], int] = {}  # (ns, vol_id) -> new writers
    bad: set[str] = set()
    for node_id in sorted(plan.node_allocation):
        for alloc in plan.node_allocation[node_id]:
            job = alloc.job or plan.job
            if job is None:
                continue
            tg = job.lookup_task_group(alloc.task_group)
            if tg is None or not tg.volumes:
                continue
            for req in tg.volumes.values():
                if req.read_only or req.type not in ("", "host"):
                    continue
                for vol in snapshot.volumes_by_name(
                    alloc.namespace, req.source
                ):
                    if vol.node_id not in ("", node_id):
                        continue
                    key = (vol.namespace, vol.id)
                    pending = writers.get(key, 0)
                    from ..structs.structs import (
                        VOLUME_ACCESS_READ_ONLY,
                        VOLUME_ACCESS_SINGLE_WRITER,
                    )

                    live_writers = sum(
                        1
                        for c in vol.write_claims()
                        if c.alloc_id not in removed
                    )
                    if vol.access_mode == VOLUME_ACCESS_READ_ONLY or (
                        vol.access_mode == VOLUME_ACCESS_SINGLE_WRITER
                        and (live_writers + pending) >= 1
                    ):
                        bad.add(node_id)
                    else:
                        writers[key] = pending + 1
                    break
    return bad


def evaluate_plan(snapshot, plan: Plan) -> PlanResult:
    """Re-verify the whole plan; return the committable subset
    (reference :400)."""
    result = PlanResult(
        node_update=dict(plan.node_update),
        node_allocation={},
        node_preemptions=dict(plan.node_preemptions),
        deployment=plan.deployment,
        deployment_updates=list(plan.deployment_updates),
    )
    # Volume single-writer admission across the WHOLE plan: the
    # feasibility screen saw committed state only, so two writers placed
    # in one plan would both pass it — count in-plan write claims here
    # and reject the overflowing node (reference: the CSI claim RPC
    # serializes this per volume; our claim point is plan apply).
    vol_rejected = _volume_overcommitted_nodes(snapshot, plan)
    rejected = False
    for node_id in plan.node_allocation:
        ok, reason = (
            (False, "volume write-claim conflict")
            if node_id in vol_rejected
            else evaluate_node_plan(snapshot, plan, node_id)
        )
        if ok:
            result.node_allocation[node_id] = plan.node_allocation[node_id]
        else:
            rejected = True
            # A rejected placement must not still evict its victims:
            # preemptions free capacity FOR that node's placements and
            # are meaningless without them.
            result.node_preemptions.pop(node_id, None)
            logger.debug("plan for node %s rejected: %s", node_id, reason)
    if rejected:
        if plan.all_at_once:
            # all-or-nothing jobs: reject the ENTIRE plan — stops,
            # preemptions, and deployment changes must not land without
            # their placements.
            result.node_allocation = {}
            result.node_update = {}
            result.node_preemptions = {}
            result.deployment = None
            result.deployment_updates = []
        result.refresh_index = snapshot.index
    return result


class PlanApplier:
    """Dequeues plans, verifies, applies through the raft layer."""

    def __init__(self, queue: PlanQueue, state, raft_apply: Callable) -> None:
        self.queue = queue
        self.state = state  # live StateStore
        self.raft_apply = raft_apply  # (msg_type, payload) -> index
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="plan-applier"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    def _loop(self) -> None:
        while not self._stop.is_set():
            item = self.queue.dequeue(timeout_s=0.2)
            if item is None:
                continue
            plan, fut = item
            try:
                result = self.apply_one(plan)
                fut.set_result(result)
            except Exception as e:  # pragma: no cover - defensive
                logger.exception("plan apply failed")
                if not fut.done():
                    fut.set_exception(e)

    def apply_one(self, plan: Plan) -> PlanResult:
        snapshot = self.state.snapshot()
        result = evaluate_plan(snapshot, plan)
        if result.is_no_op():
            return result
        result.preemption_evals = self._preemption_evals(result)
        # Normalize before the log encodes the payload: embedded Job copies
        # would serialize once PER ALLOCATION (a c2m-scale plan would pack
        # ~100k Jobs). The scheduled job version rides ONCE on the result
        # and the FSM re-attaches it to every alloc that referenced it —
        # NOT the jobs table's current version, which may have moved while
        # the plan sat in the queue, and NOT the stored alloc's old
        # version, which would silently revert in-place updates. Allocs
        # referencing some OTHER version (e.g. followup-eval annotations
        # of old allocs) keep their job embedded.
        result.job = plan.job
        if result.job is not None:
            for allocs in result.node_allocation.values():
                for a in allocs:
                    if a.job is result.job:
                        a.job = None
        index = self.raft_apply("apply_plan_results", result)
        result.alloc_index = index
        return result

    def _preemption_evals(self, result: PlanResult):
        """One follow-up eval per job losing allocs to preemption, so the
        preempted work reschedules elsewhere (reference plan_apply.go:278)."""
        from ..structs import Evaluation, generate_uuid
        from ..structs.structs import (
            EVAL_STATUS_PENDING,
            EVAL_TRIGGER_PREEMPTION,
            now_ns,
        )

        seen: set[tuple[str, str]] = set()
        for allocs in result.node_preemptions.values():
            for a in allocs:
                seen.add((a.namespace, a.job_id))
        evals = []
        for ns, job_id in seen:
            # preempted plan rows carry job=None; resolve from state
            job = self.state.job_by_id(ns, job_id)
            evals.append(
                Evaluation(
                    id=generate_uuid(),
                    namespace=ns,
                    priority=job.priority if job else 50,
                    type=job.type if job else "service",
                    triggered_by=EVAL_TRIGGER_PREEMPTION,
                    job_id=job_id,
                    status=EVAL_STATUS_PENDING,
                    create_time=now_ns(),
                    modify_time=now_ns(),
                )
            )
        return evals
