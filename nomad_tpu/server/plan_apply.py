"""Plan applier: THE serialization point of the optimistic scheduler.

Reference: nomad/plan_apply.go — planApply :71, evaluatePlan :400,
evaluateNodePlan :631. Scheduler workers race against stale snapshots; the
applier re-verifies every touched node against the LATEST state and commits
only the subset that still fits. A partial commit sets refresh_index, which
forces the worker to refresh its snapshot and retry the remainder.

The reference parallelizes per-node verification over a worker pool
(plan_apply_pool.go:18) and pipelines verification of plan N+1 with the
Raft apply of plan N (plan_apply.go:54-63). Threads buy nothing under the
GIL, so the same two overlaps are won differently here:

- per-node verification is VECTORIZED: the state store maintains an
  incremental per-node usage aggregate (state/store.py IDX_NODE_USED), so
  each touched node's re-verification is an O(1) aggregate read plus one
  numpy compare over the whole plan's node set, instead of re-summing
  every node's allocs in interpreted loops. Nodes whose fit depends on
  ports/cores/volumes take the exact per-node path (evaluate_node_plan).
- the applier PIPELINES: verification of plan N+1 runs while the raft
  commit of plan N is still in flight, against the latest snapshot with
  plan N's result overlaid (OverlaySnapshot). Before responding to N's
  worker the applier hands the commit-wait to a side thread, so the
  verify loop never blocks on replication round-trips.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

import numpy as np

from .. import trace
from ..gctune import paused_gc
from ..state.store import usage_contribution
from ..structs import Plan, PlanResult, allocs_fit
from ..structs.placement_batch import AllocRow as _row_handle
from ..structs.structs import NODE_STATUS_READY
from .plan_queue import PlanQueue

logger = logging.getLogger("nomad_tpu.plan_apply")


def _batch_rows_for_node(plan: Plan, node_id: str) -> list:
    """Materialize just one node's rows from the plan's SoA batches —
    the exact-verification path needs real Allocation views, but only
    for the (rare) nodes that fall off the vectorized fast path."""
    rows: list = []
    for b in plan.alloc_batches:
        for nid, ti, _cnt in b.touched_nodes():
            if nid == node_id:
                idx = np.nonzero(b.node_idx == ti)[0]
                rows.extend(b.row(int(i)) for i in idx)
                break
    return rows


def evaluate_node_plan(snapshot, plan: Plan, node_id: str) -> tuple[bool, str]:
    """Would this plan's changes to one node fit? (reference :631)."""
    proposed = list(plan.node_allocation.get(node_id, []))
    if plan.alloc_batches:
        proposed.extend(_batch_rows_for_node(plan, node_id))
    if not proposed:
        return True, ""  # stops/preemptions alone always apply
    node = snapshot.node_by_id(node_id)
    if node is None:
        return False, "node does not exist"
    if node.status != NODE_STATUS_READY:
        return False, f"node is {node.status}"

    existing = snapshot.allocs_by_node_terminal(node_id, False)
    remove = {a.id for a in plan.node_update.get(node_id, [])}
    remove |= {a.id for a in plan.node_preemptions.get(node_id, [])}
    update_ids = {a.id for a in proposed}
    keep = [a for a in existing if a.id not in remove and a.id not in update_ids]
    fit, dim, _ = allocs_fit(node, keep + list(proposed))
    if not fit:
        return False, dim
    return True, ""


class _VolRow:
    """A batch row's volume-claim identity (namespace, job, task group)
    — all the overcommit walk reads."""

    __slots__ = ("namespace", "job", "task_group")

    def __init__(self, namespace: str, job, task_group: str) -> None:
        self.namespace = namespace
        self.job = job
        self.task_group = task_group


def _volume_overcommitted_nodes(snapshot, plan: Plan) -> set[str]:
    """Nodes whose placements would exceed a registered volume's write
    capacity, counting claims already committed AND earlier placements in
    this same plan (first-come order by node id for determinism)."""
    if not hasattr(snapshot, "volumes_by_name"):
        return set()
    # Claims held by allocs this plan stops/evicts/replaces don't count
    # against the new placements (same rule evaluate_node_plan applies to
    # resource fit): a destructive update of the single writer must not
    # conflict with its own predecessor.
    removed: set[str] = set()
    for allocs in plan.node_update.values():
        removed.update(a.id for a in allocs)
    for allocs in plan.node_preemptions.values():
        removed.update(a.id for a in allocs)
    for allocs in plan.node_allocation.values():
        removed.update(a.id for a in allocs)  # in-place updates of selves
    writers: dict[tuple[str, str], int] = {}  # (ns, vol_id) -> new writers
    bad: set[str] = set()
    # SoA batch rows participate as (namespace, job, tg) x count per
    # node — a whole batch shares one volume-bearing task group, so no
    # rows materialize here. Batch-free plans walk node_allocation
    # directly (no per-node list copies on the eager path).
    per_node_rows: dict[str, list] = plan.node_allocation
    if plan.alloc_batches:
        merged = None
        for b in plan.alloc_batches:
            job = b.job or plan.job
            if job is None:
                continue
            tg = job.lookup_task_group(b.task_group)
            if tg is None or not tg.volumes:
                continue
            if merged is None:
                merged = per_node_rows = {
                    nid: list(allocs)
                    for nid, allocs in plan.node_allocation.items()
                }
            for nid, ti, cnt in b.touched_nodes():
                merged.setdefault(nid, []).extend(
                    _VolRow(b.namespace, job, b.task_group)
                    for _ in range(cnt)
                )
    for node_id in sorted(per_node_rows):
        for alloc in per_node_rows[node_id]:
            job = alloc.job or plan.job
            if job is None:
                continue
            tg = job.lookup_task_group(alloc.task_group)
            if tg is None or not tg.volumes:
                continue
            for req in tg.volumes.values():
                if req.read_only or req.type not in ("", "host"):
                    continue
                for vol in snapshot.volumes_by_name(
                    alloc.namespace, req.source
                ):
                    if vol.node_id not in ("", node_id):
                        continue
                    key = (vol.namespace, vol.id)
                    pending = writers.get(key, 0)
                    from ..structs.structs import (
                        VOLUME_ACCESS_READ_ONLY,
                        VOLUME_ACCESS_SINGLE_WRITER,
                    )

                    live_writers = sum(
                        1
                        for c in vol.write_claims()
                        if c.alloc_id not in removed
                    )
                    if vol.access_mode == VOLUME_ACCESS_READ_ONLY or (
                        vol.access_mode == VOLUME_ACCESS_SINGLE_WRITER
                        and (live_writers + pending) >= 1
                    ):
                        bad.add(node_id)
                    else:
                        writers[key] = pending + 1
                    break
    return bad


def _fast_path_usage(snapshot, plan: Plan, node_id: str, node,
                     contrib: Optional[dict] = None):
    """Try to express one node's re-verification as a 3-vector compare.

    Returns (cpu, mem, disk) the node would hold after the plan, or None
    when the node needs the exact path: some involved alloc carries cores
    or port asks, or the node's own reserved ports could self-collide."""
    used = snapshot.node_usage(node_id)
    if used[3] > 0:
        return None  # a committed alloc on this node has cores/ports
    rp = node.reserved.reserved_ports
    if rp and len(rp) != len(set(rp)) and node.resources.networks:
        return None  # reserved-port self-collision is ip-dependent
    cpu, mem, disk = used[0], used[1], used[2]
    proposed = plan.node_allocation.get(node_id, [])
    remove_ids = {a.id for a in plan.node_update.get(node_id, [])}
    remove_ids |= {a.id for a in plan.node_preemptions.get(node_id, [])}
    remove_ids |= {a.id for a in proposed}
    for aid in remove_ids:
        stored = snapshot.alloc_by_id(aid)
        if stored is not None and stored.node_id == node_id:
            c = usage_contribution(stored)
            if c is not None:
                cpu -= c[0]
                mem -= c[1]
                disk -= c[2]
    for alloc in proposed:
        # fresh solver placements share one AllocatedResources per group
        # (solver fast-mint): memoize the contribution walk per distinct
        # (resources, status) across the whole plan
        ar = alloc.resources
        if contrib is not None and ar is not None:
            key = (id(ar), alloc.desired_status, alloc.client_status)
            c = contrib.get(key)
            if c is None and key not in contrib:
                c = contrib[key] = usage_contribution(alloc)
        else:
            c = usage_contribution(alloc)
        if c is None:
            continue
        if c[3]:
            return None  # proposed alloc asks for cores/ports
        cpu += c[0]
        mem += c[1]
        disk += c[2]
    return (cpu, mem, disk)


def evaluate_plan(snapshot, plan: Plan) -> PlanResult:
    """Re-verify the whole plan; return the committable subset
    (reference :400).

    Vectorized: nodes whose fit is a pure cpu/mem/disk question — the
    overwhelming majority — are verified with ONE numpy compare over the
    plan's node set, reading the store's incremental per-node usage
    aggregate. Only nodes involving ports, dedicated cores, or volume
    claims re-walk their allocs (evaluate_node_plan, the exact oracle
    this fast path is differential-tested against)."""
    result = PlanResult(
        node_update=dict(plan.node_update),
        node_allocation={},
        node_preemptions=dict(plan.node_preemptions),
        deployment=plan.deployment,
        deployment_updates=list(plan.deployment_updates),
    )
    # Volume single-writer admission across the WHOLE plan: the
    # feasibility screen saw committed state only, so two writers placed
    # in one plan would both pass it — count in-plan write claims here
    # and reject the overflowing node (reference: the CSI claim RPC
    # serializes this per volume; our claim point is plan apply).
    vol_rejected = _volume_overcommitted_nodes(snapshot, plan)
    rejected = False
    rejected_nodes: set[str] = set()

    def reject(node_id: str, reason: str) -> None:
        nonlocal rejected
        rejected = True
        rejected_nodes.add(node_id)
        # A rejected placement must not still evict its victims:
        # preemptions free capacity FOR that node's placements and
        # are meaningless without them.
        result.node_preemptions.pop(node_id, None)
        logger.debug("plan for node %s rejected: %s", node_id, reason)

    # SoA batches: per-node proposed additions come straight from the
    # columns — bincount-style (count x shared row contribution), no
    # row materialization. batch rows are fast-mint by construction
    # (complex=0), so they never force a node onto the exact path by
    # themselves.
    batches = plan.alloc_batches
    batch_add: dict[str, tuple[int, int, int]] = {}
    if batches:
        for b in batches:
            c = b.row_contribution()
            for nid, _ti, cnt in b.touched_nodes():
                cur = batch_add.get(nid)
                if cur is None:
                    batch_add[nid] = (c[0] * cnt, c[1] * cnt, c[2] * cnt)
                else:
                    batch_add[nid] = (
                        cur[0] + c[0] * cnt,
                        cur[1] + c[1] * cnt,
                        cur[2] + c[2] * cnt,
                    )

    fast_ids: list[str] = []
    fast_rows: list[tuple[int, int, int, int, int, int]] = []
    slow_ids: list[str] = []
    contrib: dict = {}  # per-plan shared-resources contribution memo

    def verify_node(node_id: str, proposed) -> None:
        if node_id in vol_rejected:
            reject(node_id, "volume write-claim conflict")
            return
        add = batch_add.get(node_id)
        if not proposed and add is None:
            result.node_allocation[node_id] = proposed
            return
        node = snapshot.node_by_id(node_id)
        if node is None:
            reject(node_id, "node does not exist")
            return
        if node.status != NODE_STATUS_READY:
            reject(node_id, f"node is {node.status}")
            return
        usage = _fast_path_usage(snapshot, plan, node_id, node, contrib)
        if usage is None:
            slow_ids.append(node_id)
            return
        if add is not None:
            usage = (usage[0] + add[0], usage[1] + add[1], usage[2] + add[2])
        avail = node.available_resources()
        fast_ids.append(node_id)
        fast_rows.append(
            (usage[0], usage[1], usage[2], avail.cpu, avail.memory_mb, avail.disk_mb)
        )

    for node_id, proposed in plan.node_allocation.items():
        verify_node(node_id, proposed)
    for node_id in batch_add:
        if node_id not in plan.node_allocation:
            verify_node(node_id, [])
    if fast_rows:
        rows = np.asarray(fast_rows, dtype=np.int64)
        fits = (rows[:, :3] <= rows[:, 3:]).all(axis=1)
        for node_id, ok in zip(fast_ids, fits):
            if ok:
                if node_id in plan.node_allocation:
                    result.node_allocation[node_id] = plan.node_allocation[
                        node_id
                    ]
            else:
                reject(node_id, "resources exhausted")
    for node_id in slow_ids:
        ok, reason = evaluate_node_plan(snapshot, plan, node_id)
        if ok:
            if node_id in plan.node_allocation:
                result.node_allocation[node_id] = plan.node_allocation[node_id]
        else:
            reject(node_id, reason)

    # Batch verdicts: a rejected node drops ONLY its rows from each
    # batch (a boolean-mask view of the columns); untouched batches ride
    # through whole.
    if batches:
        committed_batches = []
        for b in batches:
            bad_tis = [
                ti
                for nid, ti, _cnt in b.touched_nodes()
                if nid in rejected_nodes
            ]
            if not bad_tis:
                committed_batches.append(b)
                continue
            keep = ~np.isin(b.node_idx, np.asarray(bad_tis, dtype=np.int32))
            if keep.any():
                committed_batches.append(b.take(keep))
        result.alloc_batches = committed_batches

    if rejected:
        if plan.all_at_once:
            # all-or-nothing jobs: reject the ENTIRE plan — stops,
            # preemptions, and deployment changes must not land without
            # their placements.
            result.node_allocation = {}
            result.node_update = {}
            result.node_preemptions = {}
            result.deployment = None
            result.deployment_updates = []
            result.alloc_batches = []
        result.refresh_index = snapshot.index
    return result


def _contribution_with_job(alloc, default_job):
    """usage_contribution for a plan alloc that may have been normalized
    (job detached onto the PlanResult): compute with the result's job
    temporarily re-attached, exactly as the FSM will see it at apply."""
    if alloc.job is None and default_job is not None and alloc.job_id == default_job.id:
        alloc.job = default_job
        try:
            return usage_contribution(alloc)
        finally:
            alloc.job = None
    return usage_contribution(alloc)


class OverlaySnapshot:
    """The latest committed snapshot with one in-flight PlanResult
    optimistically applied: what state WILL look like once the pending
    plan's raft commit lands. Plan N+1 verifies against this while plan
    N replicates — the pipelining of reference plan_apply.go:54-63,
    without blocking on snapshotMinIndex.

    Only the surface evaluate_plan reads is overlaid (allocs by id/node,
    per-node usage); everything else delegates to the base snapshot.
    Volume-touching plans never verify on an overlay (the applier drains
    the pipeline first), so volume claims always read committed state."""

    def __init__(self, base, result: PlanResult, job) -> None:
        self.base = base
        self.index = base.index
        self._placed: dict[str, object] = {}
        self._placed_by_node: dict[str, list] = {}
        self._stopped: set[str] = set()
        # node_id -> [cpu, mem, disk, complex] delta vs the base aggregate,
        # mirroring exactly what the FSM's alloc writes will do to it.
        delta: dict[str, list] = {}

        def _sub_stored(alloc_id: str, node_id: str) -> None:
            stored = base.alloc_by_id(alloc_id)
            if stored is None or stored.node_id != node_id:
                return
            c = usage_contribution(stored)
            if c is not None:
                d = delta.setdefault(node_id, [0, 0, 0, 0])
                for i in range(4):
                    d[i] -= c[i]

        for node_id, allocs in result.node_update.items():
            for a in allocs:
                self._stopped.add(a.id)
                _sub_stored(a.id, node_id)
        for node_id, allocs in result.node_preemptions.items():
            for a in allocs:
                self._stopped.add(a.id)
                _sub_stored(a.id, node_id)
        for node_id, allocs in result.node_allocation.items():
            bucket = self._placed_by_node.setdefault(node_id, [])
            for a in allocs:
                self._placed[a.id] = a
                bucket.append(a)
                _sub_stored(a.id, node_id)
                c = _contribution_with_job(a, job)
                if c is not None:
                    d = delta.setdefault(node_id, [0, 0, 0, 0])
                    for i in range(4):
                        d[i] += c[i]
        for b in result.alloc_batches:
            # SoA rows overlay as lazy handles: the usage delta comes
            # from the columns (count x shared contribution); a later
            # plan's verification materializes a row only if it actually
            # dereferences it (alloc_by_id / the exact per-node path)
            c = b.row_contribution()
            touched = b.touched_nodes()
            for nid, _ti, cnt in touched:
                d = delta.setdefault(nid, [0, 0, 0, 0])
                d[0] += c[0] * cnt
                d[1] += c[1] * cnt
                d[2] += c[2] * cnt
            ti_to_nid = {ti: nid for nid, ti, _cnt in touched}
            idx = b.node_idx
            for i, uid in enumerate(b.ids):
                h = _row_handle(b, i)
                self._placed[uid] = h
                self._placed_by_node.setdefault(
                    ti_to_nid[int(idx[i])], []
                ).append(h)
        self._usage_delta = delta

    def __getattr__(self, name):
        return getattr(self.base, name)

    def node_usage(self, node_id: str):
        base = self.base.node_usage(node_id)
        d = self._usage_delta.get(node_id)
        if d is None:
            return base
        return (base[0] + d[0], base[1] + d[1], base[2] + d[2], base[3] + d[3])

    def alloc_by_id(self, alloc_id: str):
        a = self._placed.get(alloc_id)
        if a is not None:
            return a
        a = self.base.alloc_by_id(alloc_id)
        if a is not None and alloc_id in self._stopped:
            from ..structs.structs import ALLOC_DESIRED_STATUS_STOP

            a = a.copy()
            a.desired_status = ALLOC_DESIRED_STATUS_STOP
        return a

    def allocs_by_node_terminal(self, node_id: str, terminal: bool = False):
        out = []
        for a in self.base.allocs_by_node_terminal(node_id, terminal):
            if a.id in self._placed:
                continue
            if not terminal and a.id in self._stopped:
                continue
            out.append(a)
        for a in self._placed_by_node.get(node_id, []):
            if a.terminal_status() == terminal:
                out.append(a)
        return out


def _plan_partition_key(plan: Plan) -> tuple[set[str], bool, Optional[tuple]]:
    """(touched node set, touches_volumes, job key) — the plan facts the
    conflict partition branches on. Derived once per plan; the round
    loop in _commit_merged_rounds reuses them across rounds instead of
    rebuilding the sets and re-walking volumes O(rounds x plans)."""
    nodes = (
        set(plan.node_allocation)
        | set(plan.node_update)
        | set(plan.node_preemptions)
    )
    for b in plan.alloc_batches:
        nodes.update(nid for nid, _ti, _cnt in b.touched_nodes())
    job_key = (
        (plan.job.namespace, plan.job.id) if plan.job is not None else None
    )
    return nodes, _plan_touches_volumes(plan), job_key


def partition_plan_batch(
    plans: list[Plan],
    keys: Optional[list[tuple[set, bool, Optional[tuple]]]] = None,
) -> tuple[list[int], list[int]]:
    """Per-node conflict partition of a same-snapshot plan batch.

    Returns (merged, serial) index lists. A plan joins the merged set
    when its touched node set is disjoint from every earlier merged
    plan's — disjoint node sets mean one plan's placements/stops cannot
    change another's fit, so all of them verify correctly against ONE
    snapshot and commit as one raft entry. Plans that conflict on a
    node, or touch volumes (two node-disjoint plans can still race one
    volume's write claim), fall back to the existing serial path, in
    submission order, AFTER the merged commit — so their verification
    sees the merged plans' effects and rejects/refreshes exactly as if
    everything had been serial.

    Two plans for the SAME job never merge either: the bulk commit
    collapses each round's jobs by (namespace, id), so same-job plans at
    different job versions would re-attach one plan's allocs to the
    other's version. The eval broker's one-in-flight-eval-per-job lock
    already makes this unreachable from the TPU worker, but enqueue_batch
    is public API — enforce it here rather than rely on the convention.

    keys — optional precomputed _plan_partition_key list parallel to
    plans."""
    if keys is None:
        keys = [_plan_partition_key(p) for p in plans]
    merged: list[int] = []
    serial: list[int] = []
    claimed: set[str] = set()
    claimed_jobs: set[tuple] = set()
    for i, (nodes, touches_volumes, job_key) in enumerate(keys):
        if (
            touches_volumes
            or (nodes & claimed)
            or (job_key is not None and job_key in claimed_jobs)
        ):
            serial.append(i)
            continue
        claimed |= nodes
        if job_key is not None:
            claimed_jobs.add(job_key)
        merged.append(i)
    return merged, serial


def _plan_touches_volumes(plan: Plan) -> bool:
    """Does any placement in this plan use task-group volumes? Such plans
    must verify against committed state (volume claims commit atomically
    with the plan that placed them, so an overlay could miss a pending
    single-writer claim)."""
    seen: set[tuple[int, str]] = set()
    for allocs in plan.node_allocation.values():
        for a in allocs:
            job = a.job or plan.job
            if job is None:
                continue
            key = (id(job), a.task_group)
            if key in seen:
                continue
            seen.add(key)
            tg = job.lookup_task_group(a.task_group)
            if tg is not None and tg.volumes:
                return True
    for b in plan.alloc_batches:
        # one (job, task group) per batch — no row walk
        job = b.job or plan.job
        if job is None:
            continue
        tg = job.lookup_task_group(b.task_group)
        if tg is not None and tg.volumes:
            return True
    return False


class PlanApplier:
    """Dequeues plans, verifies, applies through the raft layer.

    Pipelined (reference plan_apply.go:54-63): after submitting plan N's
    result to raft, the applier immediately verifies plan N+1 against the
    latest snapshot with N's result overlaid; a completion thread waits
    out N's commit and responds to its worker. At most one plan result is
    in flight — the depth the reference runs at."""

    def __init__(
        self,
        queue: PlanQueue,
        state,
        raft_apply: Callable,
        raft_apply_async: Optional[Callable] = None,
    ) -> None:
        self.queue = queue
        self.state = state  # live StateStore
        self.raft_apply = raft_apply  # (msg_type, payload) -> index
        # (msg_type, payload) -> (index, wait_fn) — wait_fn blocks until
        # committed+applied. None disables pipelining (serial fallback).
        self.raft_apply_async = raft_apply_async
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cthread: Optional[threading.Thread] = None
        self._cq: list = []
        self._cq_cv = threading.Condition()
        self._outstanding = 0
        # Bumped on every start(): a completion thread from a previous
        # start/stop cycle that was stuck inside wait_fn past the join
        # timeout must not touch the restarted applier's queue/counter.
        self._gen = 0
        # Set by the completion thread when a commit fails (leadership
        # loss or timeout): the raft index whose fate is unknown. The
        # overlay built from it must be discarded, and the next
        # verification first gives the state store a short grace window
        # to catch up — a TIMED-OUT commit can still land, and verifying
        # without it would double-commit its capacity.
        self._commit_failed_index = 0
        # (raft index, PlanResult, job) of the not-yet-committed plan
        self._inflight: Optional[tuple[int, PlanResult, object]] = None

    def start(self) -> None:
        self._stop.clear()
        self._inflight = None
        with self._cq_cv:
            self._gen += 1
            gen = self._gen
            self._cq = []
            self._outstanding = 0
            self._commit_failed_index = 0
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="plan-applier"
        )
        self._thread.start()
        if self.raft_apply_async is not None:
            self._cthread = threading.Thread(
                target=self._completion_loop,
                args=(gen,),
                daemon=True,
                name="plan-applier-wait",
            )
            self._cthread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cq_cv:
            self._cq_cv.notify_all()
        if self._thread:
            self._thread.join(timeout=2)
        if self._cthread:
            self._cthread.join(timeout=2)
            self._cthread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            item = self.queue.dequeue(timeout_s=0.2)
            if item is None:
                continue
            plan, fut, tref = item
            # tref: (TraceContext, parent Span) handed through the queue
            # by the submitting worker — applier-side verify/apply spans
            # land on the SAME trace, nested under its plan.submit span.
            ctx = tref[0] if tref is not None else None
            if isinstance(plan, list):
                try:
                    with trace.use(ctx):
                        self._apply_batch(plan, fut, tref)
                except Exception as e:  # pragma: no cover - defensive
                    logger.exception("plan batch apply failed")
                    for f in fut:
                        if not f.done():
                            f.set_exception(e)
                continue
            try:
                with trace.use(ctx):
                    self._apply_pipelined(plan, fut, tref)
            except Exception as e:  # pragma: no cover - defensive
                logger.exception("plan apply failed")
                if not fut.done():
                    fut.set_exception(e)

    # -- pipelined path -------------------------------------------------

    def _apply_pipelined(self, plan: Plan, fut, tref=None) -> None:
        tctx, tparent = tref if tref is not None else (None, None)
        pipelining = self.raft_apply_async is not None
        self._absorb_commit_failure()
        if pipelining and self._inflight is not None and _plan_touches_volumes(plan):
            self._drain()
            self._absorb_commit_failure()
        snapshot = self.state.snapshot()
        if self._inflight is not None:
            idx, res, job = self._inflight
            if snapshot.index >= idx:
                self._inflight = None  # committed and applied; base is current
            else:
                snapshot = OverlaySnapshot(snapshot, res, job)
        # verification + normalization allocate in bulk at c2m scale —
        # same GC-pause rationale as the solver (gctune.py). ONLY the
        # allocation burst: the blocking raft waits below must not hold
        # the process-wide collector off (the raft/store paths pause
        # around their own bursts).
        with paused_gc():
            with trace.span(tctx, "plan.verify", parent=tparent):
                result = evaluate_plan(snapshot, plan)
            if result.is_no_op():
                fut.set_result(result)
                return
            result.preemption_evals = self._preemption_evals(result)
            self._normalize(plan, result)
        if not pipelining:
            with trace.span(tctx, "plan.raft_apply", parent=tparent):
                index = self.raft_apply("apply_plan_results", result)
            result.alloc_index = index
            fut.set_result(result)
            return
        with trace.span(tctx, "plan.raft_apply", parent=tparent):
            index, wait_fn = self.raft_apply_async(
                "apply_plan_results", result
            )
        # Depth-1 pipeline: wait out the PREVIOUS commit (its replication
        # overlapped with the verification we just finished) before
        # recording this one as in flight.
        self._drain()
        self._inflight = (index, result, plan.job)
        with self._cq_cv:
            self._cq.append((index, wait_fn, result, fut))
            self._outstanding += 1
            self._cq_cv.notify_all()

    # -- merged batch path ----------------------------------------------

    @staticmethod
    def _trim_duplicate_mints(
        results: list[PlanResult], seen: set, snapshot
    ) -> int:
        """Same-eval/same-alloc-name dedup across one merged commit.

        The r15/r17 soak duplicate-alloc forensics proved both duplicate
        ids are minted by the SAME eval inside ONE merged plan-apply
        raft entry (apply_plan_results_batch — same create_index): an
        eval solved twice with both outcomes landing in one round, or
        one plan carrying a name twice across its eager rows and SoA
        batches. Per-node capacity verification cannot catch it — two
        ids for one (eval, name) are not a capacity violation — so the
        merge round guards the identity invariant itself: the FIRST
        entrant in commit order keeps the name, every later entrant is
        trimmed before the raft apply. A trimmed result gets a
        refresh_index, so its worker sees a partial commit and requeues
        the eval, which then re-reconciles against state that already
        holds the first entrant. ``seen`` spans the whole batch (all
        rounds), so a round-2 re-mint of a round-1 name trims too."""
        trimmed = 0
        for result in results:
            hit = False
            for nid, allocs in list(result.node_allocation.items()):
                keep = []
                for a in allocs:
                    if a.create_index:
                        # an UPDATE of an existing alloc (inplace,
                        # attr annotation) keeps its original minting
                        # eval_id/name — it is not a mint and two plans
                        # touching it in one batch are last-writer-wins,
                        # not duplicates
                        keep.append(a)
                        continue
                    key = (a.eval_id, a.name)
                    if key in seen:
                        trimmed += 1
                        hit = True
                        continue
                    seen.add(key)
                    keep.append(a)
                if len(keep) != len(allocs):
                    if keep:
                        result.node_allocation[nid] = keep
                    else:
                        del result.node_allocation[nid]
            if result.alloc_batches:
                new_batches = []
                for b in result.alloc_batches:
                    mask = np.ones(len(b), dtype=bool)
                    for ri, name in enumerate(b.names):
                        key = (b.eval_id, name)
                        if key in seen:
                            mask[ri] = False
                            trimmed += 1
                            hit = True
                        else:
                            seen.add(key)
                    if mask.all():
                        new_batches.append(b)
                    elif mask.any():
                        new_batches.append(b.take(mask))
                result.alloc_batches = new_batches
            if hit:
                result.refresh_index = max(
                    result.refresh_index, snapshot.index
                )
        if trimmed:
            from .. import blackbox, metrics

            metrics.incr("nomad.plan_apply.dup_mint_trimmed", trimmed)
            # flight-recorder journal: the dup-mint-invariant trigger
            # captures an incident off this counter, and the journal row
            # ties the trim to its minting evals for the timeline
            blackbox.record(
                blackbox.KIND_DUP_MINT, "plan_apply", trimmed=trimmed,
                rel=[
                    f"eval:{e}" for e in sorted(
                        {ev for ev, _ in seen}
                    )[:8]
                ],
            )
            logger.warning(
                "merged plan round minted %d duplicate (eval, name) "
                "alloc(s); trimmed the later entrant(s)", trimmed,
            )
        return trimmed

    def _commit_merged(
        self, plans: list[Plan], merged_idx: list[int], snapshot,
        tref=None, round_no: int = 0, seen_mints: Optional[set] = None,
    ) -> dict[int, PlanResult]:
        """Verify the merged (node-disjoint) subset against one snapshot
        and commit every non-no-op result as ONE raft entry backed by one
        bulk store transaction."""
        tctx, tparent = tref if tref is not None else (None, None)
        results: dict[int, PlanResult] = {}
        verified: list[tuple[int, PlanResult]] = []
        to_commit: list[tuple[int, PlanResult]] = []
        with paused_gc():
            with trace.span(
                tctx, "plan.verify", parent=tparent,
                round=round_no, plans=len(merged_idx),
            ):
                for i in merged_idx:
                    result = evaluate_plan(snapshot, plans[i])
                    if result.is_no_op():
                        results[i] = result
                        continue
                    verified.append((i, result))
            # identity guard BEFORE preemption evals / normalization: a
            # trimmed row must not leave its preemption or job wiring
            # behind (satellite: the r15/r17 duplicate-alloc race)
            self._trim_duplicate_mints(
                [r for _, r in verified],
                seen_mints if seen_mints is not None else set(),
                snapshot,
            )
            for i, result in verified:
                if result.is_no_op():
                    results[i] = result
                    continue
                result.preemption_evals = self._preemption_evals(result)
                self._normalize(plans[i], result)
                to_commit.append((i, result))
        if to_commit:
            with trace.span(
                tctx, "plan.raft_apply", parent=tparent,
                round=round_no, plans=len(to_commit),
            ):
                index = self.raft_apply(
                    "apply_plan_results_batch", [r for _, r in to_commit]
                )
            for i, r in to_commit:
                r.alloc_index = index
                results[i] = r
        return results

    def _commit_merged_rounds(
        self, plans: list[Plan], snapshot, tref=None
    ) -> tuple[dict[int, PlanResult], list[int]]:
        """Round-partitioned merged commit: each round commits the
        mutually node-disjoint prefix of the REMAINING plans as one raft
        entry, then re-snapshots so the next round's verification sees
        it. A node-conflicting plan thus still rides a bulk commit one
        round later (same optimistic-concurrency outcome as the serial
        path: it verifies against committed state that includes the
        plans that beat it, and rejects/refreshes if it lost the race)
        instead of paying an individual raft apply + store transaction.
        Volume-touching plans never merge; their indices are returned
        for the caller's true serial path."""
        from .. import metrics

        t0 = time.perf_counter()
        results: dict[int, PlanResult] = {}
        remaining = list(range(len(plans)))
        keys = [_plan_partition_key(p) for p in plans]
        merged_total = 0
        rounds = 0
        # (eval_id, alloc name) minted anywhere in this batch — the
        # duplicate-mint guard's memory across rounds
        seen_mints: set = set()
        while remaining:
            rel_merged, rel_rest = partition_plan_batch(
                [plans[i] for i in remaining],
                keys=[keys[i] for i in remaining],
            )
            if not rel_merged:
                break  # only volume plans left — serial path
            if rounds > 0:
                snapshot = self.state.snapshot()
            round_idx = [remaining[r] for r in rel_merged]
            results.update(
                self._commit_merged(
                    plans, round_idx, snapshot, tref=tref,
                    round_no=rounds, seen_mints=seen_mints,
                )
            )
            merged_total += len(round_idx)
            rounds += 1
            remaining = [remaining[r] for r in rel_rest]
        metrics.observe("nomad.plan_apply.batch_merged", merged_total)
        metrics.observe("nomad.plan_apply.batch_rounds", rounds)
        metrics.observe("nomad.plan_apply.batch_serial", len(remaining))
        metrics.observe(
            "nomad.plan_apply.batch_seconds", time.perf_counter() - t0
        )
        return results, remaining

    def _apply_batch(self, plans: list[Plan], futs: list, tref=None) -> None:
        """Queue-dequeued batch: round-partitioned merged commits for
        everything node-partitionable, serial fallback (in order) for
        the volume-touching rest.

        The batch verifies against COMMITTED state only, so any pipelined
        single-plan commit still in flight is drained first — the merged
        commit is itself one synchronous apply for N plans, which already
        amortizes what the depth-1 pipeline would have hidden."""
        self._drain()
        self._absorb_commit_failure()
        if self._stop.is_set():
            err = RuntimeError("plan applier stopping")
            for f in futs:
                if not f.done():
                    f.set_exception(err)
            return
        snapshot = self.state.snapshot()
        if self._inflight is not None:
            idx, res, job = self._inflight
            if snapshot.index >= idx:
                self._inflight = None
            else:  # pragma: no cover - drain above makes this unreachable
                snapshot = OverlaySnapshot(snapshot, res, job)
        results, serial_idx = self._commit_merged_rounds(
            plans, snapshot, tref=tref
        )
        for i, r in results.items():
            futs[i].set_result(r)
        # Volume-touching plans re-verify against post-merge state via
        # the standard (pipelined) serial path and refresh/reject exactly
        # as they always did.
        for i in serial_idx:
            try:
                self._apply_pipelined(plans[i], futs[i], tref)
            except Exception as e:  # pragma: no cover - defensive
                logger.exception("serial fallback apply failed")
                if not futs[i].done():
                    futs[i].set_exception(e)

    def apply_batch(self, plans: list[Plan]) -> list[PlanResult]:
        """Synchronous merged verify+commit of a plan batch (direct
        callers and tests; the dequeue loop routes queue batches through
        the same partition/merge core)."""
        # Same preamble as the queue batch path: a pipelined single-plan
        # commit still in flight is invisible to a fresh committed-state
        # snapshot — verifying without draining it would double-book the
        # node it landed on. No-ops when nothing is outstanding.
        self._drain()
        self._absorb_commit_failure()
        results, serial_idx = self._commit_merged_rounds(
            plans, self.state.snapshot()
        )
        for i in serial_idx:
            results[i] = self.apply_one(plans[i])
        return [results[i] for i in range(len(plans))]

    def _absorb_commit_failure(self) -> None:
        """If an in-flight commit failed, discard its overlay — after
        giving the state store a short window to catch up, since a commit
        that failed by TIMEOUT may still land and verifying without its
        effects would double-commit capacity. If the index never arrives
        the entry is presumed truncated (leadership moved): subsequent
        submits fail leader checks, so nothing stale can commit."""
        with self._cq_cv:
            failed_idx = self._commit_failed_index
            self._commit_failed_index = 0
        if not failed_idx:
            return
        try:
            self.state.snapshot_min_index(failed_idx, timeout_s=1.0)
        except TimeoutError:
            pass
        self._inflight = None

    def _drain(self) -> None:
        """Block until every submitted result has committed (or failed)
        and its worker has been answered."""
        with self._cq_cv:
            while self._outstanding > 0:
                self._cq_cv.wait(0.5)
                if self._stop.is_set():
                    return

    def _completion_loop(self, gen: int) -> None:
        while True:
            with self._cq_cv:
                while (
                    not self._cq
                    and not self._stop.is_set()
                    and gen == self._gen
                ):
                    self._cq_cv.wait(0.5)
                if gen != self._gen:
                    return  # superseded by a restart; a new thread owns _cq
                if self._stop.is_set() and not self._cq:
                    return
                index, wait_fn, result, fut = self._cq.pop(0)
            try:
                result.alloc_index = wait_fn()
                fut.set_result(result)
            except Exception as e:
                with self._cq_cv:
                    if gen == self._gen:
                        self._commit_failed_index = index
                if not fut.done():
                    fut.set_exception(e)
            finally:
                with self._cq_cv:
                    if gen == self._gen:
                        self._outstanding -= 1
                        self._cq_cv.notify_all()

    @staticmethod
    def _normalize(plan: Plan, result: PlanResult) -> None:
        # Normalize before the log encodes the payload: embedded Job copies
        # would serialize once PER ALLOCATION (a c2m-scale plan would pack
        # ~100k Jobs). The scheduled job version rides ONCE on the result
        # and the FSM re-attaches it to every alloc that referenced it —
        # NOT the jobs table's current version, which may have moved while
        # the plan sat in the queue, and NOT the stored alloc's old
        # version, which would silently revert in-place updates. Allocs
        # referencing some OTHER version (e.g. followup-eval annotations
        # of old allocs) keep their job embedded.
        result.job = plan.job
        if result.job is not None:
            for allocs in result.node_allocation.values():
                for a in allocs:
                    if a.job is result.job:
                        a.job = None
            for b in result.alloc_batches:
                # one shared job slot per batch, not one per row
                if b.job is result.job:
                    b.job = None

    def apply_one(self, plan: Plan) -> PlanResult:
        """Serial verify+commit of one plan (direct callers and tests;
        the dequeue loop runs the pipelined path)."""
        snapshot = self.state.snapshot()
        result = evaluate_plan(snapshot, plan)
        if result.is_no_op():
            return result
        result.preemption_evals = self._preemption_evals(result)
        self._normalize(plan, result)
        index = self.raft_apply("apply_plan_results", result)
        result.alloc_index = index
        return result

    def _preemption_evals(self, result: PlanResult):
        """One follow-up eval per job losing allocs to preemption, so the
        preempted work reschedules elsewhere (reference plan_apply.go:278)."""
        from ..structs import Evaluation, generate_uuids
        from ..structs.structs import (
            EVAL_STATUS_PENDING,
            EVAL_TRIGGER_PREEMPTION,
            now_ns,
        )

        seen: set[tuple[str, str]] = set()
        for allocs in result.node_preemptions.values():
            for a in allocs:
                seen.add((a.namespace, a.job_id))
        if not seen:
            return []
        evals = []
        # bulk id minting: one entropy draw + one format pass for the
        # whole preemption wave (generate_uuids, ISSUE 12 satellite)
        ids = generate_uuids(len(seen))
        for uid, (ns, job_id) in zip(ids, seen):
            # preempted plan rows carry job=None; resolve from state
            job = self.state.job_by_id(ns, job_id)
            evals.append(
                Evaluation(
                    id=uid,
                    namespace=ns,
                    priority=job.priority if job else 50,
                    type=job.type if job else "service",
                    triggered_by=EVAL_TRIGGER_PREEMPTION,
                    job_id=job_id,
                    status=EVAL_STATUS_PENDING,
                    create_time=now_ns(),
                    modify_time=now_ns(),
                )
            )
        return evals
