"""Periodic job dispatcher (cron-style child job launching).

Reference: nomad/periodic.go — PeriodicDispatch tracks periodic jobs in a
launch-time heap, forks child jobs named `<parent>/periodic-<unix>` and
creates their evals; prohibit_overlap skips a launch while a previous child
is live. The leader also persists launch times so restarts don't re-fire
(here: launch bookkeeping lives in the dispatcher and is rebuilt from state
on leadership, like the eval broker).

The cron engine is a self-contained 5-field parser (minute hour dom month
dow) supporting *, */n, a-b, and comma lists — the subset the reference's
cronexpr dependency sees in practice — plus `@every <seconds>s` specs.
"""

from __future__ import annotations

import calendar
import logging
import threading
import time
from typing import Optional

from ..structs import Evaluation, Job, generate_uuid, now_ns
from .raft_replication import LeadershipLostError, NotLeaderError
from ..structs.structs import (
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_PERIODIC_JOB,
    JOB_STATUS_DEAD,
)

logger = logging.getLogger("nomad_tpu.periodic")

PERIODIC_LAUNCH_SUFFIX = "/periodic-"


# ---------------------------------------------------------------------------
# Cron
# ---------------------------------------------------------------------------


def _parse_field(spec: str, lo: int, hi: int) -> frozenset[int]:
    out: set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", ""):
            lo2, hi2 = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            lo2, hi2 = int(a), int(b)
        else:
            lo2 = hi2 = int(part)
        if not (lo <= lo2 <= hi and lo <= hi2 <= hi):
            raise ValueError(f"cron field value out of range [{lo},{hi}]: {spec!r}")
        out.update(range(lo2, hi2 + 1, step))
    return frozenset(out)


class CronSpec:
    """5-field cron: minute hour day-of-month month day-of-week."""

    def __init__(self, spec: str) -> None:
        fields = spec.split()
        if len(fields) != 5:
            raise ValueError(f"cron spec needs 5 fields: {spec!r}")
        self.minutes = _parse_field(fields[0], 0, 59)
        self.hours = _parse_field(fields[1], 0, 23)
        self.dom = _parse_field(fields[2], 1, 31)
        self.months = _parse_field(fields[3], 1, 12)
        self.dow = _parse_field(fields[4], 0, 6)  # 0 = Sunday
        self.dom_wild = fields[2] == "*"
        self.dow_wild = fields[4] == "*"
        self._hours_sorted = sorted(self.hours)
        self._minutes_sorted = sorted(self.minutes)

    def _day_match(self, y: int, mo: int, d: int) -> bool:
        # python weekday(): Monday=0 → cron Sunday=0 conversion
        wd = (calendar.weekday(y, mo, d) + 1) % 7
        dom_ok = d in self.dom
        dow_ok = wd in self.dow
        if self.dom_wild and self.dow_wild:
            return True
        if self.dom_wild:
            return dow_ok
        if self.dow_wild:
            return dom_ok
        return dom_ok or dow_ok  # standard cron OR semantics

    def next_after(self, ts: float) -> float:
        """Next matching epoch-second strictly after ts (UTC).

        Walks at day granularity (skipping whole non-matching months), so
        sparse specs like Feb-29 cost thousands of iterations, not the
        ~520k minute steps a naive walk needs — this runs inside the raft
        apply path via PeriodicDispatch.add, so it must stay cheap.
        """
        t = time.gmtime(int(ts) - int(ts) % 60 + 60)
        y, mo, d, h, mi = t.tm_year, t.tm_mon, t.tm_mday, t.tm_hour, t.tm_min
        for _ in range(366 * 6):  # day-granularity bound, ~6 years
            if mo not in self.months:
                # jump to the 1st of the next month
                mo += 1
                if mo > 12:
                    mo, y = 1, y + 1
                d, h, mi = 1, 0, 0
                continue
            if self._day_match(y, mo, d):
                for hh in self._hours_sorted:
                    if hh < h:
                        continue
                    for mm in self._minutes_sorted:
                        if hh == h and mm < mi:
                            continue
                        return calendar.timegm((y, mo, d, hh, mm, 0, 0, 0, 0))
                    h, mi = hh + 1, 0  # no minute left this hour
            d += 1
            h, mi = 0, 0
            if d > calendar.monthrange(y, mo)[1]:
                d = 1
                mo += 1
            if mo > 12:
                mo = 1
                y += 1
        raise ValueError("no cron match within 6 years")


def next_launch(periodic, after_ts: float) -> float:
    """Next launch time for a PeriodicConfig, epoch seconds."""
    spec = periodic.spec.strip()
    if spec.startswith("@every"):
        parts = spec.split(None, 1)
        if len(parts) != 2:
            raise ValueError(f"@every spec needs a duration: {spec!r}")
        dur = parts[1].strip()
        mult = {"s": 1, "m": 60, "h": 3600}.get(dur[-1])
        if mult is None:
            raise ValueError(
                f"@every duration needs an s/m/h suffix: {dur!r}"
            )
        seconds = float(dur[:-1]) * mult
        if seconds <= 0:
            # A non-positive period would fire a child on every poll pass.
            raise ValueError(f"@every duration must be positive: {dur!r}")
        return after_ts + seconds
    return CronSpec(spec).next_after(after_ts)


# ---------------------------------------------------------------------------
# Dispatcher
# ---------------------------------------------------------------------------


class PeriodicDispatch:
    """Tracks periodic jobs and launches due children.

    raft_apply-driven like every other leader subsystem; `run_once(now)`
    fires everything due, so tests control the clock.
    """

    def __init__(self, state, raft_apply, poll_interval_s: float = 1.0) -> None:
        self.state = state
        self.raft_apply = raft_apply
        self.poll_interval_s = poll_interval_s
        self._tracked: dict[tuple[str, str], Job] = {}
        self._next: dict[tuple[str, str], float] = {}
        self._lock = threading.Lock()
        # Serializes child-launch id probes. Separate from _lock:
        # raft_apply re-enters add() via the FSM job-upsert
        # side-channel, which takes _lock. The raft write itself
        # happens OUTSIDE this lock (nomad-vet NV-lock-blocking): a
        # quorum round-trip under it would stall force_launch RPCs
        # behind the timer thread (and vice versa) for seconds during
        # leadership churn. Ids claimed but not yet visible in the
        # state store live in _launch_reserved.
        self._launch_lock = threading.Lock()
        self._launch_reserved: set[tuple[str, str]] = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self.restore()
        # Fresh Event per incarnation (see drainer.start): a thread that
        # outlives join(timeout) polls its own event and still exits.
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(self._stop,), daemon=True,
            name="periodic-dispatch"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            self._tracked.clear()
            self._next.clear()

    def _run(self, stop: threading.Event) -> None:
        while not stop.wait(self.poll_interval_s):
            try:
                self.run_once(time.time())
            except Exception:
                logger.exception("periodic dispatch pass failed")

    def restore(self) -> None:
        """Track all live periodic jobs (reference leader.go
        restorePeriodicDispatcher)."""
        for job in self.state.jobs_by_periodic():
            self.add(job)

    # -- tracking (FSM side-channel: job register/deregister) ----------

    def add(self, job: Job) -> None:
        if not job.is_periodic() or job.stopped():
            self.remove(job.namespace, job.id)
            return
        with self._lock:
            self._tracked[job.ns_id()] = job
            self._next[job.ns_id()] = next_launch(job.periodic, time.time())

    def remove(self, namespace: str, job_id: str) -> None:
        with self._lock:
            self._tracked.pop((namespace, job_id), None)
            self._next.pop((namespace, job_id), None)

    def tracked(self) -> list[Job]:
        with self._lock:
            return list(self._tracked.values())

    # -- launching -----------------------------------------------------

    def run_once(self, now_ts: float) -> int:
        """Launch every tracked job whose next fire time has passed."""
        due: list[Job] = []
        with self._lock:
            for key, when in list(self._next.items()):
                if when <= now_ts:
                    try:
                        self._next[key] = next_launch(
                            self._tracked[key].periodic, now_ts
                        )
                    except ValueError:
                        # A spec with no future fire time can't wedge the
                        # pass (or hot-loop): untrack it.
                        logger.exception("periodic job %s untracked", key)
                        self._tracked.pop(key, None)
                        self._next.pop(key, None)
                        continue
                    due.append(self._tracked[key])
        launched = 0
        for job in due:
            if job.periodic.prohibit_overlap and self._has_live_child(job):
                logger.info(
                    "periodic job %s skipped: prohibit_overlap and a child "
                    "is still running",
                    job.id,
                )
                continue
            self.create_child(job, int(now_ts))
            launched += 1
        return launched

    def force_launch(self, namespace: str, job_id: str) -> str:
        """`job periodic force` — immediate launch regardless of schedule."""
        with self._lock:
            job = self._tracked.get((namespace, job_id))
        if job is None:
            job = self.state.job_by_id(namespace, job_id)
        if job is None or not job.is_periodic() or job.stopped():
            raise KeyError(f"{job_id} is not a tracked periodic job")
        return self.create_child(job, int(time.time()))

    def create_child(self, parent: Job, launch_ts: int) -> str:
        """Fork `<parent>/periodic-<ts>` + eval (reference periodic.go
        createEval/deriveJob)."""
        child = parent.copy()
        # Second-granularity launch ids can collide (force_launch racing a
        # scheduled fire); the probe + reservation are atomic under the
        # launch lock, and the bump loop skips both committed children
        # and ids another launch has claimed but not yet applied — so a
        # collision can't silently upsert over an existing child. The
        # raft write runs OUTSIDE the lock: a reserved id keeps racers
        # off it without holding a lock across the quorum round-trip.
        with self._launch_lock:
            ts = launch_ts
            while True:
                cid = f"{parent.id}{PERIODIC_LAUNCH_SUFFIX}{ts}"
                key = (parent.namespace, cid)
                if (
                    key not in self._launch_reserved
                    and self.state.job_by_id(parent.namespace, cid) is None
                ):
                    break
                ts += 1
            self._launch_reserved.add(key)
        child.id = cid
        child.name = child.id
        child.parent_id = parent.id
        child.periodic = None
        child.status = ""
        ev = Evaluation(
            id=generate_uuid(),
            namespace=child.namespace,
            priority=child.priority,
            type=child.type,
            triggered_by=EVAL_TRIGGER_PERIODIC_JOB,
            job_id=child.id,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
        try:
            # raft_apply returns only after commit+apply, so the state
            # store sees the child before the reservation is dropped —
            # the probe above can never miss a committed launch.
            self.raft_apply("job_register", (child, ev))
        except Exception as exc:
            # Only a pre-submit leadership refusal is known NOT to have
            # reached the log. Every other failure is outcome-unknown —
            # LeadershipLostError and timeouts raise while the entry may
            # still be replicating and can commit after the raise — so
            # the reservation is kept: releasing it would let a racer
            # probe (not reserved, not yet in state), claim the same id,
            # and silently upsert over the late-committing child. The
            # kept entry just steers future launches to ts+1.
            if isinstance(exc, NotLeaderError) and not isinstance(
                exc, LeadershipLostError
            ):
                with self._launch_lock:
                    self._launch_reserved.discard(key)
            raise
        with self._launch_lock:
            self._launch_reserved.discard(key)
        return child.id

    def _has_live_child(self, parent: Job) -> bool:
        for child in self.state.jobs_by_parent(parent.namespace, parent.id):
            if child.status != JOB_STATUS_DEAD:
                return True
        return False
