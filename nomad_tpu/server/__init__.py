from .blocked_evals import BlockedEvals
from .core_sched import CoreScheduler, core_eval
from .deployment_watcher import DeploymentsWatcher
from .drainer import NodeDrainer
from .eval_broker import EvalBroker
from .periodic import CronSpec, PeriodicDispatch, next_launch
from .heartbeat import HeartbeatTimers, rate_scaled_interval
from .plan_apply import PlanApplier, evaluate_node_plan, evaluate_plan
from .plan_queue import PlanQueue
from .raft import FSM, InmemLog
from .server import Server
from .worker import TPUBatchWorker, Worker, WorkerPlanner
