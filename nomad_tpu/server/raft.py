"""Replicated log + FSM layer.

Reference: nomad/fsm.go (~45 message types applied to the state store) +
hashicorp/raft. Round-1 scope: a single-node ordered log whose apply path
runs through the same FSM dispatch a multi-node deployment will use —
Phase 2 swaps `InmemLog` for a real replicated log (leader election,
append-entries over the RPC fabric, snapshot install) without touching the
FSM or any caller.

Every state mutation in the server goes through `raft_apply(type, payload)`
— nothing writes the state store directly — exactly the reference's
discipline (fsm.go:210-306 dispatch).
"""

from __future__ import annotations

import pickle
import threading
from typing import Callable, Optional

from .. import trace
from ..gctune import paused_gc
from ..state import StateStore
from ..structs import (
    Allocation,
    Deployment,
    Evaluation,
    Job,
    PlanResult,
)


class FSM:
    """Applies committed log entries to the state store.

    Message types mirror the reference's MessageType set (structs.go:68-120
    / fsm.go dispatch) with snake_case names.
    """

    def __init__(self, state: StateStore) -> None:
        self.state = state
        # side-channels the leader wires up (reference fsm.go:746: the FSM
        # pokes the eval broker / blocked evals on apply)
        self.on_eval_update: Optional[Callable] = None
        self.on_node_update: Optional[Callable] = None
        self.on_alloc_client_update: Optional[Callable] = None
        self.on_job_upsert: Optional[Callable] = None  # periodic tracking
        self.on_volume_release: Optional[Callable] = None  # blocked-eval poke
        self._handlers = {
            "noop": lambda index, payload: None,  # leader election barrier
            # operator snapshot restore rides the log so every replica
            # swaps state at the same point (reference SnapshotRestore);
            # indexes rebase to the log entry's index so monotonicity
            # holds regardless of where the snapshot came from
            "snapshot_restore": self._apply_snapshot_restore,
            "node_register": self._apply_node_register,
            # fleet-scale batch forms: one raft entry covers N nodes
            # (mass-reconnect registration storms, heartbeat-wheel
            # expiry storms — server.py NodeRegisterBatcher /
            # _invalidate_heartbeat_batch)
            "node_register_batch": self._apply_node_register_batch,
            "node_batch_update_status": self._apply_node_status_batch,
            "node_deregister": self._apply_node_deregister,
            "node_update_status": self._apply_node_status,
            "node_update_drain": self._apply_node_drain,
            "node_update_eligibility": self._apply_node_eligibility,
            "job_register": self._apply_job_register,
            "job_deregister": self._apply_job_deregister,
            "eval_update": self._apply_eval_update,
            "eval_delete": self._apply_eval_delete,
            "alloc_update": self._apply_alloc_update,
            "alloc_client_update": self._apply_alloc_client_update,
            "alloc_update_desired_transition": self._apply_desired_transition,
            "apply_plan_results": self._apply_plan_results,
            "apply_plan_results_batch": self._apply_plan_results_batch,
            "deployment_upsert": self._apply_deployment_upsert,
            "deployment_status_update": self._apply_deployment_status,
            "deployment_delete": self._apply_deployment_delete,
            "deployment_promote": self._apply_deployment_promote,
            "deployment_alloc_health": self._apply_deployment_alloc_health,
            "batch_node_drain_update": self._apply_batch_drain,
            "acl_policy_upsert": lambda i, p: self.state.upsert_acl_policies(i, p),
            "acl_policy_delete": lambda i, p: self.state.delete_acl_policies(i, p),
            "acl_token_upsert": lambda i, p: self.state.upsert_acl_tokens(i, p),
            "acl_token_delete": lambda i, p: self.state.delete_acl_tokens(i, p),
            "namespace_upsert": lambda i, p: self.state.upsert_namespace(i, p),
            "namespace_delete": lambda i, p: self.state.delete_namespace(i, p),
            "volume_register": lambda i, p: self.state.upsert_volume(i, p),
            "volume_deregister": lambda i, p: self.state.delete_volume(
                i, p[0], p[1]
            ),
            "volume_claim_release": self._apply_volume_release,
            "service_upsert": lambda i, p: (
                self.state.upsert_service_registrations(i, p)
            ),
            "service_delete": lambda i, p: (
                self.state.delete_service_registrations(i, p)
            ),
            "service_delete_alloc": lambda i, p: (
                self.state.delete_services_by_alloc(i, p)
            ),
            "secret_upsert": lambda i, p: self.state.upsert_secret(i, p),
            "summaries_reconcile": lambda i, p: (
                self.state.reconcile_job_summaries(i)
            ),
            "job_scaling_event": lambda i, p: (
                self.state.upsert_scaling_event(
                    i, p["namespace"], p["job_id"], p["group"], p["event"]
                )
            ),
            "operator_config_upsert": lambda i, p: (
                self.state.upsert_operator_config(i, p[0], p[1])
            ),
            "secret_delete": lambda i, p: self.state.delete_secret(
                i, p[0], p[1]
            ),
        }

    def apply(self, index: int, msg_type: str, payload) -> object:
        handler = self._handlers.get(msg_type)
        if handler is None:
            raise ValueError(f"unknown raft message type {msg_type!r}")
        return handler(index, payload)

    # -- handlers ------------------------------------------------------

    def _apply_node_register(self, index: int, node) -> None:
        self.state.upsert_node(index, node)
        if self.on_node_update:
            self.on_node_update(node)

    def _apply_node_register_batch(self, index: int, nodes: list) -> None:
        self.state.upsert_nodes(index, nodes)
        if self.on_node_update:
            for node in nodes:
                self.on_node_update(node)

    def _apply_node_status_batch(self, index: int, payload) -> None:
        node_ids, status = payload
        self.state.update_node_statuses(index, node_ids, status)
        if self.on_node_update:
            for node_id in node_ids:
                self.on_node_update(self.state.node_by_id(node_id))

    def _apply_node_deregister(self, index: int, node_id: str) -> None:
        self.state.delete_node(index, node_id)

    def _apply_node_status(self, index: int, payload) -> None:
        node_id, status = payload
        self.state.update_node_status(index, node_id, status)
        if self.on_node_update:
            self.on_node_update(self.state.node_by_id(node_id))

    def _apply_node_drain(self, index: int, payload) -> None:
        node_id, drain, mark_eligible = payload
        self.state.update_node_drain(index, node_id, drain, mark_eligible)

    def _apply_node_eligibility(self, index: int, payload) -> None:
        node_id, eligibility = payload
        self.state.update_node_eligibility(index, node_id, eligibility)
        if self.on_node_update:
            self.on_node_update(self.state.node_by_id(node_id))

    def _apply_job_register(self, index: int, payload) -> None:
        job, eval_obj = payload
        self.state.upsert_job(index, job)
        if self.on_job_upsert:
            self.on_job_upsert(
                self.state.job_by_id(job.namespace, job.id),
                (job.namespace, job.id),
            )
        if eval_obj is not None:
            self.state.upsert_evals(index, [eval_obj])
            if self.on_eval_update:
                self.on_eval_update([eval_obj])

    def _apply_job_deregister(self, index: int, payload) -> None:
        namespace, job_id, purge, eval_obj = payload
        if purge:
            self.state.delete_job(index, namespace, job_id)
        else:
            job = self.state.job_by_id(namespace, job_id)
            if job is not None:
                stopped = job.copy()
                stopped.stop = True
                self.state.upsert_job(index, stopped)
        if self.on_job_upsert:
            self.on_job_upsert(
                self.state.job_by_id(namespace, job_id), (namespace, job_id)
            )
        if eval_obj is not None:
            self.state.upsert_evals(index, [eval_obj])
            if self.on_eval_update:
                self.on_eval_update([eval_obj])

    def _apply_eval_update(self, index: int, evals: list[Evaluation]) -> None:
        self.state.upsert_evals(index, evals)
        if self.on_eval_update:
            self.on_eval_update(evals)

    def _apply_eval_delete(self, index: int, payload) -> None:
        eval_ids, alloc_ids = payload
        self.state.delete_evals(index, eval_ids, alloc_ids)

    def _apply_alloc_update(self, index: int, allocs: list[Allocation]) -> None:
        self.state.upsert_allocs(index, allocs)

    def _apply_alloc_client_update(self, index: int, allocs) -> None:
        self.state.update_allocs_from_client(index, allocs)
        if self.on_alloc_client_update:
            self.on_alloc_client_update(allocs)

    def _apply_desired_transition(self, index: int, payload) -> None:
        transitions, evals = payload
        self.state.update_alloc_desired_transition(index, transitions, evals)
        if evals and self.on_eval_update:
            self.on_eval_update(evals)

    def _apply_snapshot_restore(self, index: int, data: bytes) -> None:
        self.state.restore_from(data)
        self.state.rebase_indexes(index)

    def _apply_plan_results(self, index: int, result: PlanResult) -> None:
        self.state.upsert_plan_results(index, result)
        # Preempted jobs reschedule via their follow-up evals
        # (reference fsm.go ApplyPlanResults → upsertEvals side channel).
        if result.preemption_evals and self.on_eval_update:
            self.on_eval_update(result.preemption_evals)

    def _apply_plan_results_batch(
        self, index: int, results: list[PlanResult]
    ) -> None:
        """N node-disjoint plan results committed as one log entry (the
        batched plan applier's merged commit — one store transaction)."""
        self.state.upsert_plan_results_batch(index, results)
        evs = [e for r in results for e in r.preemption_evals]
        if evs and self.on_eval_update:
            self.on_eval_update(evs)

    def _apply_deployment_upsert(self, index: int, deployment: Deployment) -> None:
        self.state.upsert_deployment(index, deployment)

    def _apply_deployment_status(self, index: int, update) -> None:
        self.state.update_deployment_status(index, update)

    def _apply_deployment_delete(self, index: int, ids: list[str]) -> None:
        self.state.delete_deployment(index, ids)

    def _apply_deployment_promote(self, index: int, payload) -> None:
        """(deployment_id, groups|None, eval) — reference fsm.go
        ApplyDeploymentPromotion."""
        deployment_id, groups, eval_obj = payload
        self.state.update_deployment_promotion(index, deployment_id, groups, eval_obj)
        if eval_obj is not None and self.on_eval_update:
            self.on_eval_update([eval_obj])

    def _apply_deployment_alloc_health(self, index: int, payload) -> None:
        """dict payload — reference fsm.go ApplyDeploymentAllocHealth
        (health set + optional status update + optional job revert, atomic)."""
        self.state.update_alloc_deployment_health(
            index,
            payload["deployment_id"],
            payload.get("healthy_ids", []),
            payload.get("unhealthy_ids", []),
            payload.get("status_update"),
            payload.get("eval"),
            payload.get("revert_job"),
        )
        ev = payload.get("eval")
        if ev is not None and self.on_eval_update:
            self.on_eval_update([ev])

    def _apply_volume_release(self, index: int, payload) -> None:
        if isinstance(payload, dict):
            # scoped form (volume detach): one volume only
            released = self.state.release_volume_claims_scoped(
                index,
                payload["namespace"],
                payload["volume_id"],
                list(payload["alloc_ids"]),
            )
        else:
            released = self.state.release_volume_claims(
                index, list(payload)
            )
        if released and self.on_volume_release:
            # A freed claim can make a blocked single-writer job feasible
            # again; the leader re-runs blocked evals.
            self.on_volume_release()

    def _apply_batch_drain(self, index: int, payload) -> None:
        # {node_id: DrainStrategy|None}
        for node_id, drain in payload.items():
            self.state.update_node_drain(index, node_id, drain)


class InmemLog:
    """Single-node ordered log. Serial, durable-in-memory; snapshot() dumps
    the entries for tests and for the Phase-2 replication layer to seed
    followers."""

    def __init__(self, fsm: FSM, start_index: int = 0) -> None:
        self.fsm = fsm
        self._lock = threading.Lock()
        # start_index: first entry gets start_index+1 — lets a log wrap a
        # state store that already holds indexed writes (bench harnesses).
        self._index = start_index
        self._entries: list[tuple[int, str, object]] = []

    @property
    def last_index(self) -> int:
        with self._lock:
            return self._index

    def apply(self, msg_type: str, payload) -> int:
        """Append + apply. Returns the entry's index.

        The log keeps an encoded copy (the replication/restart source of
        truth) but the local FSM applies the SUBMITTED payload directly —
        leader-direct apply. Decoding 10^5 structs the caller already
        holds in memory was the plan pipeline's single largest cost;
        skipping it is safe because (a) submitted payloads transfer
        ownership to the FSM (the same contract the reference's
        plan-owned allocs follow — the store stamps them in place), and
        (b) decode(pack(x)) == x is the codec's differentially-tested
        invariant, so followers replaying the encoded entry converge on
        identical state (tests/test_raft.py leader-direct equivalence).
        """
        from .. import codec, metrics
        import time as _time

        tracing = trace.enabled() and trace.current() is not None
        apply_t0 = _time.monotonic_ns()
        with paused_gc():
            t0 = _time.monotonic_ns() if tracing else 0
            raw = codec.pack(payload)
            if tracing:
                trace.stage("raft.encode", _time.monotonic_ns() - t0)
            with self._lock:
                self._index += 1
                index = self._index
                self._entries.append((index, msg_type, raw))
            t0 = _time.monotonic_ns() if tracing else 0
            self.fsm.apply(index, msg_type, payload)
            if tracing:
                trace.stage("fsm.apply", _time.monotonic_ns() - t0)
        # one observation per raft entry (entries batch many payloads,
        # so this is far off the per-alloc hot loop): encode + append +
        # fsm apply — the commit half of every state mutation
        metrics.time_ns(
            "nomad.raft.apply_seconds", _time.monotonic_ns() - apply_t0
        )
        return index

    def apply_async(self, msg_type: str, payload):
        """Async-apply contract: (index, wait_fn). Single-node in-memory
        apply is synchronous, so the waiter is already resolved — the plan
        applier's pipeline degenerates to serial here, which is correct."""
        index = self.apply(msg_type, payload)
        return index, (lambda: index)

    def entries_since(self, index: int) -> list[tuple[int, str, object]]:
        with self._lock:
            return [e for e in self._entries if e[0] > index]

    def snapshot_bytes(self) -> bytes:
        with self._lock:
            return pickle.dumps((self._index, self._entries))
