"""Durable raft storage: log + stable state + FSM snapshot on disk.

Reference: the Go tree wires hashicorp/raft-boltdb as the LogStore and
StableStore and streams FSM snapshots to a snapshot dir
(nomad/server.go:1210 setupRaft, nomad/fsm.go:1367 Snapshot /
:1860 Persist, helper/snapshot/). Here one SQLite file (same engine as
the client's state DB) carries all three roles:

  log(idx, term, msg_type, payload)   — the replicated log
  stable(key, value)                  — current_term / voted_for (§5.1:
                                        votes MUST survive restarts or a
                                        node can vote twice in a term)
  snapshot(id=1, last_index, last_term, data) — latest FSM snapshot

Entry payloads ride the same msgpack codec as the RPC fabric, so
anything that can be replicated can be persisted by construction.

Recovery contract (load()): the FSM is restored from the snapshot, then
the log tail replays as the cluster re-commits it — commit_index is
deliberately NOT persisted; a restarted node learns it from the next
leader's AppendEntries (standard Raft: the leader's no-op barrier entry
re-commits the prefix).
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Optional

from .. import faultplane
from .raft_replication import LogEntry


class RaftLogStore:
    def __init__(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        # Fault-plane identity (faultplane.py): injected fsync
        # failures / slow-disk rules match this label (the owning
        # node's id when run under ChaosCluster).
        self.chaos_label = ""
        self._lock = threading.Lock()
        # Exclusive advisory lock: two agents sharing a data_dir would
        # silently interleave terms/votes/logs (raft-boltdb fails fast on
        # its file lock; so do we).
        import fcntl

        self._lockfile = open(path + ".lock", "w")
        try:
            fcntl.flock(self._lockfile, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            self._lockfile.close()
            raise RuntimeError(
                f"raft store {path} is locked — is another server agent "
                f"using this data_dir?"
            ) from e
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        # NORMAL loses at most the tail of the WAL on power loss — the
        # raft protocol tolerates a truncated suffix (it simply re-
        # replicates); it does NOT tolerate torn pages, which WAL rules out.
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.executescript(
            """
            CREATE TABLE IF NOT EXISTS log (
                idx INTEGER PRIMARY KEY,
                term INTEGER NOT NULL,
                msg_type TEXT NOT NULL,
                payload BLOB
            );
            CREATE TABLE IF NOT EXISTS stable (
                key TEXT PRIMARY KEY,
                value BLOB
            );
            CREATE TABLE IF NOT EXISTS snapshot (
                id INTEGER PRIMARY KEY CHECK (id = 1),
                last_index INTEGER NOT NULL,
                last_term INTEGER NOT NULL,
                data BLOB
            );
            """
        )
        self._db.commit()

    # -- stable store ---------------------------------------------------

    def set_state(self, term: int, voted_for: Optional[str]) -> None:
        if faultplane.plane is not None:
            faultplane.plane.on_disk(self.chaos_label, "state")
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO stable(key, value) VALUES ('term', ?)",
                (str(term),),
            )
            self._db.execute(
                "INSERT OR REPLACE INTO stable(key, value) VALUES ('voted_for', ?)",
                (voted_for or "",),
            )
            self._db.commit()

    def get_state(self) -> tuple[int, Optional[str]]:
        with self._lock:
            rows = dict(
                self._db.execute("SELECT key, value FROM stable").fetchall()
            )
        term = int(rows.get("term") or 0)
        voted = rows.get("voted_for") or None
        return term, voted

    # -- log ------------------------------------------------------------

    def append(self, entries: list[LogEntry]) -> None:
        if not entries:
            return
        # Injected fsync failure / slow disk (faultplane.py): raised
        # BEFORE the write, so a "failed" append is never durable — the
        # caller must treat it exactly like a torn write that rolled
        # back, which is what the raft layer's error paths assume.
        if faultplane.plane is not None:
            faultplane.plane.on_disk(self.chaos_label, "append")
        with self._lock:
            self._db.executemany(
                "INSERT OR REPLACE INTO log(idx, term, msg_type, payload) "
                "VALUES (?, ?, ?, ?)",
                # e.payload is already the packed command bytes
                # (LogEntry contract) — written verbatim.
                [(e.index, e.term, e.msg_type, e.payload) for e in entries],
            )
            self._db.commit()

    def truncate_from(self, index: int) -> None:
        """Drop entries with idx >= index (conflict truncation)."""
        with self._lock:
            self._db.execute("DELETE FROM log WHERE idx >= ?", (index,))
            self._db.commit()

    def compact_to(self, index: int) -> None:
        """Drop entries with idx <= index (snapshot compaction)."""
        with self._lock:
            self._db.execute("DELETE FROM log WHERE idx <= ?", (index,))
            self._db.commit()

    # -- snapshot -------------------------------------------------------

    def store_snapshot(self, data: bytes, last_index: int, last_term: int) -> None:
        if faultplane.plane is not None:
            faultplane.plane.on_disk(self.chaos_label, "snapshot")
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO snapshot(id, last_index, last_term, data) "
                "VALUES (1, ?, ?, ?)",
                (last_index, last_term, data),
            )
            self._db.execute("DELETE FROM log WHERE idx <= ?", (last_index,))
            self._db.commit()

    def load_snapshot(self) -> Optional[tuple[bytes, int, int]]:
        with self._lock:
            row = self._db.execute(
                "SELECT data, last_index, last_term FROM snapshot WHERE id = 1"
            ).fetchone()
        if row is None:
            return None
        return row[0], row[1], row[2]

    def load_log(self) -> list[LogEntry]:
        with self._lock:
            rows = self._db.execute(
                "SELECT idx, term, msg_type, payload FROM log ORDER BY idx"
            ).fetchall()
        return [
            LogEntry(idx, term, msg_type, payload)
            for idx, term, msg_type, payload in rows
        ]

    def close(self) -> None:
        with self._lock:
            self._db.close()
            self._lockfile.close()  # releases the flock
