"""Multi-node Raft replication over the RPC fabric.

Reference: the Go tree vendors hashicorp/raft and wires it in
nomad/server.go:1210 (setupRaft) with a dedicated stream transport
(nomad/raft_rpc.go); the FSM is nomad/fsm.go. This is a from-scratch Raft
(Ongaro & Ousterhout, "In Search of an Understandable Consensus
Algorithm") — elections with randomized timeouts, log replication with
the AppendEntries consistency check, majority commit restricted to
current-term entries (§5.4.2), and InstallSnapshot for lagging followers.

Departures from the reference's transport, deliberate: raft RPCs ride the
same framed-msgpack fabric as everything else (`Raft.*` endpoint methods)
instead of a dedicated byte-stream layer — the fabric already pipelines,
and one transport keeps the failure model uniform.

The FSM contract is unchanged from the single-node path (raft.py): apply()
is only ever invoked with committed entries, in order, exactly once per
index on a given store. `RaftNode.apply()` blocks until commit, then
returns the entry's index — the same contract `Server.raft_apply` had with
InmemLog, so the whole control plane is replication-agnostic.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from .. import codec, metrics
from ..gctune import paused_gc
from ..rpc import ConnPool
from .raft import FSM

logger = logging.getLogger("nomad_tpu.raft")

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class NotLeaderError(Exception):
    """Raised BEFORE a write reached the log (or after it provably did
    not commit): safe for callers to retry against the new leader."""

    def __init__(self, leader_addr: Optional[tuple[str, int]]):
        self.leader_addr = leader_addr
        super().__init__(f"not the leader (leader hint: {leader_addr})")


class LeadershipLostError(NotLeaderError):
    """Deposed AFTER the entry was appended and replicating: the write's
    outcome is UNKNOWN (the new leader may still commit it). Subclasses
    NotLeaderError so churn backoff paths treat it the same, but the
    RPC forwarder must NOT auto-retry it — a retry could double-apply a
    write that did commit."""


@dataclass
class LogEntry:
    """payload is the msgpack-ENCODED command, packed once on the leader at
    append time. Storing bytes (not live objects) means (a) the FSM decodes
    a fresh object graph per apply, so the state store can take ownership of
    applied structs without aliasing the log, (b) replication sends the same
    bytes to every follower instead of re-packing per peer per send, and
    (c) the durable store writes them verbatim."""

    index: int
    term: int
    msg_type: str
    payload: bytes


class RaftEndpoint:
    """RPC surface registered as `Raft` on the fabric."""

    def __init__(self, node: "RaftNode") -> None:
        self._node = node

    def request_vote(self, args):
        return self._node._handle_request_vote(args)

    def append_entries(self, args):
        return self._node._handle_append_entries(args)

    def install_snapshot(self, args):
        return self._node._handle_install_snapshot(args)


class RaftNode:
    """One Raft participant. Owns the log and drives the FSM.

    Timers (defaults sized for in-process clusters; production configs
    scale them up): heartbeat every `heartbeat_ms`, election timeout
    randomized in [election_ms, 2*election_ms].
    """

    def __init__(
        self,
        node_id: str,
        fsm: FSM,
        pool: ConnPool,
        advertise: tuple[str, int],
        peers: dict[str, tuple[str, int]],
        heartbeat_ms: int = 60,
        election_ms: int = 250,
        bootstrap_expect: int = 1,
        snapshot_threshold: int = 8192,
        snapshot_fn: Optional[Callable[[], bytes]] = None,
        restore_fn: Optional[Callable[[bytes], None]] = None,
        on_leader_change: Optional[Callable[[bool], None]] = None,
        store=None,
    ) -> None:
        self.node_id = node_id
        self.fsm = fsm
        self.pool = pool
        self.advertise = advertise
        # peers maps node_id -> rpc addr for every OTHER member
        self.peers = dict(peers)
        # Elections only start once the known cluster reaches this size
        # (reference bootstrap_expect): a blank server joining an existing
        # cluster must never elect itself leader of a cluster of one.
        # 0 ⇒ never self-bootstrap (wait to be adopted via raft_add_peer).
        self.bootstrap_expect = bootstrap_expect
        self.heartbeat_s = heartbeat_ms / 1000.0
        self.election_s = election_ms / 1000.0
        self.snapshot_threshold = snapshot_threshold
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.on_leader_change = on_leader_change

        # Warm the native codec while no lock exists yet: the first
        # pack() otherwise happens under _lock (_become_leader_locked
        # packs the barrier entry) and a cold fastpack build would
        # stall the node mid-election (nomad-vet NV-lock-blocking).
        codec.warm_native()
        self._lock = threading.RLock()
        self._commit_cv = threading.Condition(self._lock)
        # Persistent state. With a `store` (raft_store.RaftLogStore,
        # SQLite — the reference's raft-boltdb analog) the term/vote/log/
        # snapshot survive restarts per §5.1; without one (in-process
        # test clusters) everything is memory-only.
        self.store = store
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self._log: list[LogEntry] = []  # log[i] has index snapshot_index+i+1
        self._snap_last_index = 0
        self._snap_last_term = 0
        self._snap_bytes: Optional[bytes] = None
        if store is not None:
            self.current_term, self.voted_for = store.get_state()
            snap = store.load_snapshot()
            if snap is not None:
                self._snap_bytes, self._snap_last_index, self._snap_last_term = snap
            self._log = store.load_log()
            # Drop any stale prefix a crash may have left behind the
            # persisted snapshot.
            self._log = [e for e in self._log if e.index > self._snap_last_index]
        # Volatile state
        self.state = FOLLOWER
        self.commit_index = 0
        self.last_applied = 0
        if store is not None and self._snap_bytes is not None:
            # Rebuild the FSM from the persisted snapshot; the log tail
            # replays once the next leader re-commits it (no-op barrier).
            if restore_fn is not None:
                restore_fn(self._snap_bytes)
            self.commit_index = self._snap_last_index
            self.last_applied = self._snap_last_index
        self.leader_id: Optional[str] = None
        self._last_heartbeat = time.monotonic()
        self._votes: set[str] = set()
        # How many times THIS node won an election (the process-global
        # nomad.raft.leader_changes counter mixes every in-process node
        # and counts step-downs too; per-node won-election counts let a
        # chaos scenario bound leadership churn exactly: sum of deltas
        # across a cluster == elections that happened).
        self.leadership_transitions = 0
        # Leader volatile state
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}
        self._repl_wake: dict[str, threading.Event] = {}
        # peer id -> monotonic time of its last RPC response to us
        # (leader-side CheckQuorum input, see _handle_request_vote's
        # disruptive-server guard)
        self._peer_contact: dict[str, float] = {}
        # Leader-direct apply stash: index -> (term, original payload).
        # The local FSM applies the submitted object instead of decoding
        # its own encoded entry (decode of a 10^5-alloc plan dwarfed the
        # whole apply); the encoded log remains the replication source of
        # truth and followers still decode, which converges because
        # decode(pack(x)) == x is differentially tested. Entries are
        # keyed by (index, term) so a deposed leader's truncated indexes
        # can never resolve to a stale payload; the stash clears on
        # step-down.
        self._direct_payloads: dict[int, tuple[int, object]] = {}

        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # Leadership transitions are delivered IN ORDER on one dispatcher
        # thread — firing them on ad-hoc threads could run a revoke before
        # the establish it follows, leaving leader subsystems on a follower.
        self._leader_events: "queue.Queue[Optional[bool]]" = queue.Queue()
        # Bumped by InstallSnapshot so an in-flight apply batch of stale
        # entries is discarded instead of landing on top of restored state;
        # the mutex serializes individual FSM applies against the restore
        # itself (the epoch check alone can't cover an apply in progress).
        self._restore_epoch = 0
        self._fsm_mutex = threading.Lock()
        # Index of the no-op barrier this node appended when it last
        # became leader; wait_for_replay() blocks on it.
        self._barrier_index = 0
        self.endpoint = RaftEndpoint(self)

    # ------------------------------------------------------------------
    # log helpers (all under lock)

    def _persist_state_locked(self) -> None:
        if self.store is not None:
            self.store.set_state(self.current_term, self.voted_for)

    def _last_log_index(self) -> int:
        return self._log[-1].index if self._log else self._snap_last_index

    def _last_log_term(self) -> int:
        return self._log[-1].term if self._log else self._snap_last_term

    def _entry_at(self, index: int) -> Optional[LogEntry]:
        i = index - self._snap_last_index - 1
        if 0 <= i < len(self._log):
            return self._log[i]
        return None

    def _term_at(self, index: int) -> Optional[int]:
        if index == 0:
            return 0
        if index == self._snap_last_index:
            return self._snap_last_term
        e = self._entry_at(index)
        return e.term if e else None

    # ------------------------------------------------------------------
    # lifecycle

    def start(self) -> None:
        # A deliberate single-node cluster needs no timeout dance: elect
        # immediately (dev mode / tests would otherwise wait 1-2s).
        if not self.peers and self.bootstrap_expect == 1:
            self._start_election()
        t = threading.Thread(target=self._ticker, name=f"raft-tick-{self.node_id}", daemon=True)
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._apply_loop, name=f"raft-apply-{self.node_id}", daemon=True)
        t.start()
        self._threads.append(t)
        t = threading.Thread(
            target=self._leader_change_loop,
            name=f"raft-leadership-{self.node_id}",
            daemon=True,
        )
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self._leader_events.put(None)
        with self._commit_cv:
            self._commit_cv.notify_all()
            wakes = list(self._repl_wake.values())
        for ev in wakes:
            ev.set()
        for t in self._threads:
            t.join(timeout=2)

    def _emit_leader_change(self, is_leader: bool) -> None:
        if self.on_leader_change:
            self._leader_events.put(is_leader)

    def _leader_change_loop(self) -> None:
        last: Optional[bool] = None
        while True:
            ev = self._leader_events.get()
            if ev is None:
                return
            if ev == last:
                continue
            last = ev
            try:
                self.on_leader_change(ev)
            except Exception:
                logger.exception("%s: leader-change callback failed", self.node_id)

    # ------------------------------------------------------------------
    # public write path

    def apply(self, msg_type: str, payload, timeout_s: float = 10.0):
        """Append on the leader, replicate, block until committed AND
        applied locally. Returns the entry index."""
        t0 = time.perf_counter()
        index, term = self.apply_submit(msg_type, payload)
        out = self.apply_wait(index, term, timeout_s)
        # same name as InmemLog.apply (raft.py): encode + replicate +
        # commit + local fsm apply, whichever log backs the server
        metrics.observe(
            "nomad.raft.apply_seconds", time.perf_counter() - t0
        )
        return out

    def apply_submit(self, msg_type: str, payload) -> tuple[int, int]:
        """Append on the leader and kick replication WITHOUT waiting for
        the commit. Returns (index, term) for apply_wait. This is what
        lets the plan applier verify plan N+1 while plan N replicates."""
        # Encode OUTSIDE the lock: packing a large plan payload under
        # _lock would stall the replication loops' heartbeats and get the
        # leader deposed. The bytes depend only on the payload.
        with paused_gc():
            raw = codec.pack(payload)
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_addr())
            index = self._last_log_index() + 1
            term = self.current_term
            entry = LogEntry(index, term, msg_type, raw)
            self._log.append(entry)
            if self.store is not None:
                try:
                    self.store.append([entry])
                except Exception:
                    # A failed durable append must not leave the entry in
                    # the in-memory log: it would replicate and commit an
                    # entry this node forgets on restart.
                    self._log.pop()
                    raise
            self._direct_payloads[index] = (term, payload)
            self._match_index[self.node_id] = index
            for ev in self._repl_wake.values():
                ev.set()
            if not self.peers:
                self._advance_commit_locked()
        return index, term

    def apply_wait(self, index: int, term: int, timeout_s: float = 10.0) -> int:
        """Block until a submitted entry is committed and applied locally."""
        deadline = time.monotonic() + timeout_s
        with self._commit_cv:
            while self.last_applied < index:
                # A leader's log in its own term is append-only, so staying
                # LEADER at `term` guarantees our entry is still at `index`.
                # Any truncation implies a follower interlude (term bump),
                # which this check catches even if we re-won in between.
                if self.state != LEADER or self.current_term != term:
                    # Deposed mid-wait with the entry already appended
                    # and replicating: the new leader may yet commit it,
                    # so the outcome is UNKNOWN — callers must not
                    # auto-retry (LeadershipLostError, not the
                    # retry-safe NotLeaderError).
                    raise LeadershipLostError(self.leader_addr())
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"raft apply timed out at index {index}")
                self._commit_cv.wait(remaining)
            # Applied. Still leader at `term` ⇒ our own-term log is
            # append-only ⇒ the applied entry at `index` is ours: done
            # (this also covers entries already compacted into a
            # snapshot, where the term can no longer be read).
            if self.state == LEADER and self.current_term == term:
                return index
            # Deposed after the apply. The write still succeeded iff the
            # entry at `index` carries our term — applied implies
            # committed, and committed entries never truncate (erroring
            # on a durable write would make retry-hardened callers
            # re-submit it). A different term there means ours was
            # truncated pre-commit: definitely not applied, retry-safe.
            t_at = self._term_at(index)
            if t_at == term:
                return index
            if t_at is None:
                # compacted below the snapshot while deposed: ownership
                # can no longer be verified — outcome unknown
                raise LeadershipLostError(self.leader_addr())
            raise NotLeaderError(self.leader_addr())

    # -- membership changes (single-server-at-a-time, via the log) ------

    def add_peer(self, peer_id: str, addr: tuple[str, int]) -> None:
        """Leader-only: adopt a new member (reference leader.go
        addRaftPeer). Rides the log so every replica converges on the
        same configuration at the same index."""
        if peer_id == self.node_id or peer_id in self.peers:
            return
        self.apply("raft_add_peer", (peer_id, tuple(addr)))

    def remove_peer(self, peer_id: str) -> None:
        """Leader-only (reference removeRaftPeer / autopilot cleanup)."""
        if peer_id not in self.peers:
            return
        self.apply("raft_remove_peer", peer_id)

    def _apply_peer_change(
        self, msg_type: str, payload, epoch: Optional[int] = None
    ) -> None:
        with self._lock:
            if epoch is not None and self._restore_epoch != epoch:
                return
            if msg_type == "raft_add_peer":
                peer_id, addr = payload
                addr = tuple(addr)
                if peer_id == self.node_id or peer_id in self.peers:
                    return
                self.peers[peer_id] = addr
                if self.state == LEADER:
                    self._next_index[peer_id] = self._last_log_index() + 1
                    self._match_index[peer_id] = 0
                    self._repl_wake[peer_id] = threading.Event()
                    t = threading.Thread(
                        target=self._replicate_loop,
                        args=(peer_id,),
                        name=f"raft-repl-{self.node_id}-{peer_id}",
                        daemon=True,
                    )
                    t.start()
                    self._threads.append(t)
            else:
                peer_id = payload
                self.peers.pop(peer_id, None)
                self._next_index.pop(peer_id, None)
                self._match_index.pop(peer_id, None)
                wake = self._repl_wake.pop(peer_id, None)
                if wake is not None:
                    wake.set()  # its replicate loop exits on next check
                if self.state == LEADER:
                    self._advance_commit_locked()

    def leader_addr(self) -> Optional[tuple[str, int]]:
        if self.leader_id is None:
            return None
        if self.leader_id == self.node_id:
            return self.advertise
        return self.peers.get(self.leader_id)

    def is_leader(self) -> bool:
        return self.state == LEADER

    def wait_for_replay(self, timeout_s: float = 30.0) -> bool:
        """Leader-only: block until the local FSM has applied this
        leader's own no-op barrier — i.e. every entry committed before
        (or at) this leadership is reflected in local state. This is the
        reference's establish-leadership barrier (leader.go Barrier):
        without it a fresh leader restores broker state from a
        MID-REPLAY snapshot and can re-run evaluations whose effects are
        still in the unapplied log tail (duplicate allocs). Returns
        False when deposed or timed out — the caller must then skip
        stale-state reads (a revoke is on its way, or state isn't
        trustworthy yet)."""
        deadline = time.monotonic() + timeout_s
        with self._commit_cv:
            while True:
                if self._stop.is_set() or self.state != LEADER:
                    return False
                if self.last_applied >= self._barrier_index:
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                # bounded slice: _commit_cv is notified on applies and
                # step-downs, the slice only guards a missed stop()
                self._commit_cv.wait(min(remaining, 0.25))

    @property
    def last_index(self) -> int:
        with self._lock:
            return self._last_log_index()

    # ------------------------------------------------------------------
    # ticker: election timeout + heartbeats

    def _ticker(self) -> None:
        timeout = self._rand_election_timeout()
        while not self._stop.is_set():
            time.sleep(self.heartbeat_s / 2)
            try:
                with self._lock:
                    state = self.state
                    elapsed = time.monotonic() - self._last_heartbeat
                if state == LEADER:
                    continue  # replication threads heartbeat
                if elapsed >= timeout:
                    with self._lock:
                        quorum_known = (
                            self.bootstrap_expect > 0
                            and len(self.peers) + 1 >= self.bootstrap_expect
                        )
                    if quorum_known:
                        self._start_election()
                    timeout = self._rand_election_timeout()
            except Exception:
                # The ticker is the node's heartbeat-of-last-resort; it
                # must survive anything (a dead ticker = a zombie node
                # that can never call an election again).
                logger.exception("%s: ticker iteration failed", self.node_id)

    def _rand_election_timeout(self) -> float:
        return self.election_s * (1.0 + random.random())

    def _start_election(self) -> None:
        with self._lock:
            self.state = CANDIDATE
            self.current_term += 1
            term = self.current_term
            self.voted_for = self.node_id
            self._persist_state_locked()
            self._votes = {self.node_id}
            self.leader_id = None
            self._last_heartbeat = time.monotonic()
            last_idx = self._last_log_index()
            last_term = self._last_log_term()
            peers = dict(self.peers)  # snapshot: applies mutate in place
        logger.debug("%s: starting election term %d", self.node_id, term)
        if self._won_locked_check():
            return
        for peer_id, addr in peers.items():
            threading.Thread(
                target=self._solicit_vote,
                args=(peer_id, addr, term, last_idx, last_term),
                name=f"raft-vote-{peer_id}",
                daemon=True,
            ).start()

    def _solicit_vote(self, peer_id, addr, term, last_idx, last_term) -> None:
        try:
            resp = self.pool.call(
                addr,
                "Raft.request_vote",
                {
                    "term": term,
                    "candidate_id": self.node_id,
                    "last_log_index": last_idx,
                    "last_log_term": last_term,
                },
                timeout_s=self.election_s,
            )
        except Exception:
            return
        with self._lock:
            if resp["term"] > self.current_term:
                self._become_follower_locked(resp["term"])
                return
            if (
                self.state != CANDIDATE
                or self.current_term != term
                or not resp.get("granted")
            ):
                return
            self._votes.add(peer_id)
        self._won_locked_check()

    def _won_locked_check(self) -> bool:
        with self._lock:
            cluster_n = len(self.peers) + 1
            if self.state == CANDIDATE and len(self._votes) * 2 > cluster_n:
                self._become_leader_locked()
                return True
        return False

    def _become_leader_locked(self) -> None:
        logger.info("%s: leader for term %d", self.node_id, self.current_term)
        self.state = LEADER
        self.leader_id = self.node_id
        self.leadership_transitions += 1
        # Churn observability: every local leadership transition counts
        # (step-downs increment in _become_follower_locked). A climbing
        # rate on `operator top` is the signature of election storms.
        metrics.incr("nomad.raft.leader_changes")
        # Barrier no-op in our own term: commit can only count current-term
        # entries (§5.4.2), so without this a fresh leader would sit on
        # fully-replicated prior-term entries until the next real write.
        barrier = LogEntry(
            self._last_log_index() + 1, self.current_term, "noop",
            codec.pack(None),
        )
        self._log.append(barrier)
        if self.store is not None:
            try:
                self.store.append([barrier])
            except Exception:
                # Cannot lead without a durable barrier: keeping it only
                # in memory while later appends persist would leave a
                # HOLE in the stored log, and load_log's contiguity
                # assumption (log[i] has index snap+i+1) would read
                # shifted entries on restart. Abort this leadership —
                # the cluster re-elects (possibly us, once the disk
                # recovers).
                logger.exception(
                    "%s: barrier persist failed; abandoning leadership",
                    self.node_id,
                )
                self._log.pop()
                self.state = FOLLOWER
                self.leader_id = None
                return
        # Everything at or below this index is this leader's replay
        # debt: wait_for_replay() blocks until the local FSM has applied
        # it, i.e. this replica's state reflects every prior commit.
        self._barrier_index = barrier.index
        last = self._last_log_index()
        self._next_index = {p: last + 1 for p in self.peers}
        self._match_index = {p: 0 for p in self.peers}
        self._match_index[self.node_id] = last
        self._repl_wake = {p: threading.Event() for p in self.peers}
        for peer_id in self.peers:
            t = threading.Thread(
                target=self._replicate_loop,
                args=(peer_id,),
                name=f"raft-repl-{self.node_id}-{peer_id}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        if not self.peers:
            self._advance_commit_locked()
        self._emit_leader_change(True)

    def _become_follower_locked(self, term: int) -> None:
        was_leader = self.state == LEADER
        if was_leader:
            metrics.incr("nomad.raft.leader_changes")
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_state_locked()
        self.state = FOLLOWER
        # A deposed leader's uncommitted tail may be truncated and its
        # indexes rewritten by the new leader — drop the direct-apply
        # stash (the term check would reject them anyway).
        self._direct_payloads.clear()
        # Forget the old leader until an AppendEntries names the new one —
        # a deposed leader keeping itself as the hint would make forwards
        # loop back to itself.
        self.leader_id = None
        self._last_heartbeat = time.monotonic()
        if was_leader:
            self._emit_leader_change(False)
        self._commit_cv.notify_all()

    # ------------------------------------------------------------------
    # leader replication

    def _replicate_loop(self, peer_id: str) -> None:
        """One thread per follower: push entries / heartbeats, retry on
        mismatch by walking next_index back (§5.3)."""
        addr = self.peers[peer_id]
        while not self._stop.is_set():
            with self._lock:
                if self.state != LEADER or peer_id not in self.peers:
                    return
                term = self.current_term
                next_idx = self._next_index[peer_id]
                if next_idx <= self._snap_last_index:
                    self._send_snapshot(peer_id, addr, term)
                    continue
                prev_idx = next_idx - 1
                prev_term = self._term_at(prev_idx)
                if prev_term is None:
                    self._send_snapshot(peer_id, addr, term)
                    continue
                off = next_idx - self._snap_last_index - 1
                entries = self._log[off : off + 512]
                req = {
                    "term": term,
                    "leader_id": self.node_id,
                    "prev_log_index": prev_idx,
                    "prev_log_term": prev_term,
                    "entries": [
                        (e.index, e.term, e.msg_type, e.payload) for e in entries
                    ],
                    "leader_commit": self.commit_index,
                }
                wake = self._repl_wake[peer_id]
                wake.clear()
            try:
                resp = self.pool.call(
                    addr, "Raft.append_entries", req, timeout_s=2.0
                )
            except Exception:
                wake.wait(self.heartbeat_s)
                continue
            with self._lock:
                # any response (success or not) proves the peer is
                # reachable — CheckQuorum input for the vote guard
                self._peer_contact[peer_id] = time.monotonic()
                if self.state != LEADER or self.current_term != term:
                    return
                if resp["term"] > self.current_term:
                    self._become_follower_locked(resp["term"])
                    return
                if resp.get("success"):
                    if entries:
                        self._match_index[peer_id] = entries[-1].index
                        self._next_index[peer_id] = entries[-1].index + 1
                        self._advance_commit_locked()
                    more = self._last_log_index() >= self._next_index[peer_id]
                else:
                    # Conflict: follower tells us how far back to jump.
                    hint = resp.get("conflict_index")
                    self._next_index[peer_id] = max(
                        1, hint if hint else self._next_index[peer_id] - 1
                    )
                    more = True
            if not more:
                wake.wait(self.heartbeat_s)

    def _send_snapshot(self, peer_id: str, addr, term: int) -> None:
        """Called under lock; releases it around the network call."""
        if self._snap_bytes is None and self.snapshot_fn is not None:
            self._take_snapshot_locked()
        snap = (self._snap_bytes, self._snap_last_index, self._snap_last_term)
        # Snapshot carries the member configuration too: a blank follower
        # restored from snapshot must know the full peer set (the add-peer
        # log entries it would have learned it from were compacted away).
        config = {self.node_id: list(self.advertise)}
        config.update({p: list(a) for p, a in self.peers.items()})
        self._lock.release()
        try:
            resp = self.pool.call(
                addr,
                "Raft.install_snapshot",
                {
                    "term": term,
                    "leader_id": self.node_id,
                    "last_included_index": snap[1],
                    "last_included_term": snap[2],
                    "data": snap[0],
                    "config": config,
                },
                timeout_s=10.0,
            )
        except Exception:
            resp = None
            time.sleep(self.heartbeat_s)
        finally:
            self._lock.acquire()
        if resp is None:
            return
        if resp["term"] > self.current_term:
            self._become_follower_locked(resp["term"])
            return
        self._next_index[peer_id] = snap[1] + 1
        self._match_index[peer_id] = snap[1]

    def _advance_commit_locked(self) -> None:
        """Majority-match commit, current-term entries only (§5.4.2)."""
        cluster_n = len(self.peers) + 1
        matches = sorted(
            self._match_index.get(p, 0) for p in list(self.peers) + [self.node_id]
        )
        # Highest index replicated on a strict majority: with matches
        # ascending, that's matches[n - majority] = matches[(n-1)//2]
        # (e.g. n=4 ⇒ 3 replicas needed ⇒ matches[1], NOT matches[2]).
        majority_idx = matches[(cluster_n - 1) // 2]
        # walk down to the highest current-term entry <= majority_idx
        n = majority_idx
        while n > self.commit_index:
            if self._term_at(n) == self.current_term:
                self.commit_index = n
                self._commit_cv.notify_all()
                break
            n -= 1

    # ------------------------------------------------------------------
    # apply loop (leader and followers)

    def _apply_loop(self) -> None:
        while not self._stop.is_set():
            with self._commit_cv:
                while (
                    self.last_applied >= self.commit_index
                    and not self._stop.is_set()
                ):
                    self._commit_cv.wait(0.5)
                if self._stop.is_set():
                    return
                start = self.last_applied + 1
                end = self.commit_index
                epoch = self._restore_epoch
                off = start - self._snap_last_index - 1
                entries = self._log[off : off + (end - start + 1)] if off >= 0 else []
            with paused_gc():
                for e in entries:
                    # A snapshot restore while we were applying makes the
                    # rest of this batch stale — re-applying old entries on
                    # top of newer restored state would corrupt it.
                    direct = self._direct_payloads.pop(e.index, None)
                    if e.msg_type in ("raft_add_peer", "raft_remove_peer"):
                        # Raft-level config change: needs _lock, not the FSM
                        # mutex (taking _lock under _fsm_mutex would deadlock
                        # against InstallSnapshot's _lock → _fsm_mutex order).
                        self._apply_peer_change(
                            e.msg_type, codec.unpack(e.payload), epoch
                        )
                        continue
                    with self._fsm_mutex:
                        if self._restore_epoch != epoch:
                            break
                        try:
                            # Leader-direct: the submitted payload applies
                            # as-is when this entry is provably ours (term
                            # match); anything else decodes fresh — the FSM
                            # (and through it the state store) owns applied
                            # structs outright either way.
                            if direct is not None and direct[0] == e.term:
                                payload = direct[1]
                            else:
                                payload = codec.unpack(e.payload)
                            self.fsm.apply(e.index, e.msg_type, payload)
                        except Exception:
                            logger.exception(
                                "%s: FSM apply failed at %d",
                                self.node_id, e.index,
                            )
            with self._commit_cv:
                if self._restore_epoch == epoch and end > self.last_applied:
                    self.last_applied = end
                    self._commit_cv.notify_all()
                self._maybe_compact_locked()

    def _take_snapshot_locked(self) -> None:
        if self.snapshot_fn is None:
            return
        idx = self.last_applied
        term = self._term_at(idx)
        if term is None:
            return
        self._snap_bytes = self.snapshot_fn()
        self._snap_last_index = idx
        self._snap_last_term = term
        self._log = [e for e in self._log if e.index > idx]
        if self.store is not None:
            # store_snapshot also compacts the persisted log prefix
            self.store.store_snapshot(self._snap_bytes, idx, term)
        logger.info("%s: snapshot at index %d", self.node_id, idx)

    def _maybe_compact_locked(self) -> None:
        if (
            self.snapshot_fn is not None
            and len(self._log) >= self.snapshot_threshold
            and self.last_applied > self._snap_last_index
        ):
            self._take_snapshot_locked()

    # ------------------------------------------------------------------
    # RPC handlers (follower side)

    def _quorum_contact_fresh_locked(self) -> bool:
        """Leader-side CheckQuorum: have we heard RPC responses from a
        majority within the election timeout? (self counts)"""
        if not self.peers:
            return True
        now = time.monotonic()
        live = 1 + sum(
            1
            for p in self.peers
            if now - self._peer_contact.get(p, 0.0) < self.election_s
        )
        return live * 2 > len(self.peers) + 1

    def _handle_request_vote(self, args):
        with self._lock:
            term = args["term"]
            if term < self.current_term:
                return {"term": self.current_term, "granted": False}
            if term > self.current_term:
                # Disruptive-server guard (Ongaro §4.2.3 / hashicorp
                # CheckQuorum): a node that cannot HEAR the cluster (dead
                # listener, healing partition) election-times-out on a
                # loop and solicits votes at ever-climbing terms; without
                # this guard each request deposes the healthy leader and
                # the cluster churns for as long as the node stays deaf.
                # A leader in contact with a quorum, and a follower that
                # heard its leader within the minimum election timeout,
                # both IGNORE the higher term (no step-down, no term
                # bump, no vote). Real failovers are unaffected: once
                # heartbeats actually stop, the guard lapses before any
                # follower's own election timer fires.
                if self.state == LEADER and self._quorum_contact_fresh_locked():
                    return {"term": self.current_term, "granted": False}
                if (
                    self.state != LEADER
                    and self.leader_id is not None
                    and time.monotonic() - self._last_heartbeat < self.election_s
                ):
                    return {"term": self.current_term, "granted": False}
                self._become_follower_locked(term)
            up_to_date = args["last_log_term"] > self._last_log_term() or (
                args["last_log_term"] == self._last_log_term()
                and args["last_log_index"] >= self._last_log_index()
            )
            if up_to_date and self.voted_for in (None, args["candidate_id"]):
                self.voted_for = args["candidate_id"]
                # The vote MUST hit disk before the reply (§5.1): a
                # rebooted node that forgot its vote could vote twice
                # in one term and elect two leaders.
                self._persist_state_locked()
                self._last_heartbeat = time.monotonic()
                return {"term": self.current_term, "granted": True}
            return {"term": self.current_term, "granted": False}

    def _handle_append_entries(self, args):
        with self._lock:
            term = args["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if term > self.current_term or self.state != FOLLOWER:
                self._become_follower_locked(term)
            self.leader_id = args["leader_id"]
            self._last_heartbeat = time.monotonic()

            prev_idx = args["prev_log_index"]
            prev_term = args["prev_log_term"]
            our_term = self._term_at(prev_idx)
            if our_term is None:
                # We don't have prev_idx at all — tell the leader where
                # our log ends so it can jump straight there.
                return {
                    "term": self.current_term,
                    "success": False,
                    "conflict_index": self._last_log_index() + 1,
                }
            if our_term != prev_term:
                # Find the first index of the conflicting term.
                ci = prev_idx
                while ci > self._snap_last_index + 1 and self._term_at(ci - 1) == our_term:
                    ci -= 1
                return {
                    "term": self.current_term,
                    "success": False,
                    "conflict_index": ci,
                }
            appended: list[LogEntry] = []
            for raw in args["entries"]:
                idx, eterm, msg_type, payload = raw
                existing = self._entry_at(idx)
                if existing is not None:
                    if existing.term == eterm:
                        continue
                    # conflict: truncate from idx on
                    keep = idx - self._snap_last_index - 1
                    self._log = self._log[:keep]
                    if self.store is not None:
                        self.store.truncate_from(idx)
                if idx == self._last_log_index() + 1:
                    entry = LogEntry(idx, eterm, msg_type, payload)
                    self._log.append(entry)
                    appended.append(entry)
            if appended and self.store is not None:
                # Persist before acking: success tells the leader these
                # entries are stable on this follower.
                try:
                    self.store.append(appended)
                except Exception:
                    # Roll the in-memory suffix back too: otherwise the
                    # leader's RETRY finds the entries already present,
                    # skips the store write, and acks entries that never
                    # hit disk — a full-cluster restart would then lose
                    # an acked write (exposed by the chaos fsync fault).
                    keep = appended[0].index - self._snap_last_index - 1
                    self._log = self._log[:keep]
                    raise
            if args["leader_commit"] > self.commit_index:
                # §5.3: clamp to the index of the last entry COVERED BY
                # THIS REQUEST, not our last log index — we may hold
                # stale divergent entries beyond the appended batch that
                # must not be marked committed before truncation.
                last_new = (
                    args["entries"][-1][0] if args["entries"] else prev_idx
                )
                new_commit = min(args["leader_commit"], last_new)
                if new_commit > self.commit_index:
                    self.commit_index = new_commit
                    self._commit_cv.notify_all()
            return {"term": self.current_term, "success": True}

    def _handle_install_snapshot(self, args):
        with self._lock:
            term = args["term"]
            if term < self.current_term:
                return {"term": self.current_term}
            self._become_follower_locked(term)
            self.leader_id = args["leader_id"]
            self._last_heartbeat = time.monotonic()
            last_idx = args["last_included_index"]
            last_term = args["last_included_term"]
            if last_idx <= self._snap_last_index or last_idx <= self.last_applied:
                return {"term": self.current_term}
            with self._fsm_mutex:
                self._restore_epoch += 1
                if self.restore_fn is not None and args["data"] is not None:
                    self.restore_fn(args["data"])
            config = args.get("config")
            if config:
                self.peers = {
                    p: tuple(a) for p, a in config.items() if p != self.node_id
                }
            self._snap_bytes = args["data"]
            self._snap_last_index = last_idx
            self._snap_last_term = last_term
            self._log = [e for e in self._log if e.index > last_idx]
            if self.store is not None and args["data"] is not None:
                self.store.store_snapshot(args["data"], last_idx, last_term)
            self.commit_index = max(self.commit_index, last_idx)
            self.last_applied = max(self.last_applied, last_idx)
            self._commit_cv.notify_all()
            return {"term": self.current_term}
