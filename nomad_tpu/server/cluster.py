"""Clustered server: Raft-replicated control plane over the RPC fabric.

Reference: nomad/server.go (server wiring: RPC at :1073, Raft at :1210),
nomad/rpc.go `forward` (any server forwards writes to the leader),
nomad/leader.go leadership transitions driving leader-only subsystems, and
client/servers manager (clients fail over between servers).

One ClusterServer = one `nomad agent -server` process-equivalent:
  * a core `Server` (state store, FSM, brokers, schedulers, watchers);
  * a `RaftNode` replicating every state mutation;
  * an `RPCServer` exposing Raft.* plus the public endpoints
    (Job/Node/Eval/Alloc/Deployment/Status);
  * leadership changes from raft enable/disable the leader-only
    subsystems, exactly like establishLeadership/revokeLeadership.

Writes land on any server and are forwarded to the leader; reads are
served from the local replica (the reference's default-consistent reads
forward too — our forwarding helper takes `local_ok` to choose).

Scheduler workers run only on the leader — a deliberate departure from
the reference (which runs workers on every server, submitting plans to
the leader over Plan.Submit): the TPU batch solver wants all pending
evals in one dense batch on the chip, so spreading workers across
followers would shrink batches and add a network hop per plan. Horizontal
scheduler scale comes from the solver's device mesh instead (SURVEY.md
§2.9 point 1).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Optional

from .. import blackbox, clusterobs, metrics
from ..retry import FORWARD_POLICY, call_with_retry
from ..rpc import ConnPool, RPCError, RPCServer
from .. import faultplane
from ..structs import Allocation, Job, Node
from .membership import Membership
from .raft_replication import LeadershipLostError, NotLeaderError, RaftNode
from .server import Server

logger = logging.getLogger("nomad_tpu.cluster")


def _is_leaderless_error(e: BaseException) -> bool:
    """Errors that mean 'the cluster is between leaders' — safe to retry
    because they are raised BEFORE the write reaches the log (a local
    NotLeaderError, or the remote's NotLeaderError/no-leader travelling
    back as an RPCError string). A dial to a dead leader's address
    (connection refused — the crash-failover case) and an injected
    chaos drop are likewise pre-delivery. A generic ConnectionError
    ('connection closed' mid-flight) is NOT retried: the request may
    already have been applied and the response lost. LeadershipLostError
    (deposed AFTER the entry was replicating — outcome unknown) is the
    explicit do-not-retry variant, locally and as its RPC string."""
    if isinstance(e, LeadershipLostError):
        return False
    if isinstance(e, RPCError) and "LeadershipLostError" in str(e):
        return False
    if isinstance(e, (NotLeaderError, ConnectionRefusedError)):
        return True
    if isinstance(e, faultplane.InjectedRPCError):
        return True
    if isinstance(e, RPCError):
        msg = str(e)
        return "NotLeaderError" in msg or "no cluster leader" in msg
    return False


class _Forwarder:
    """Endpoint helper: run locally on the leader, else forward the same
    RPC to the leader (reference nomad/rpc.go forward). Leaderless
    windows (elections, leadership transfer) retry under the shared
    RetryPolicy instead of failing the caller: each attempt re-resolves
    the leader hint, so a request that lands mid-election sticks around
    just long enough to follow the new leader."""

    def __init__(self, cs: "ClusterServer") -> None:
        self.cs = cs

    def _forward(self, method: str, args, local_fn, local_ok: bool = False):
        cs = self.cs

        def attempt():
            if local_ok or cs.raft.is_leader():
                return local_fn(args)
            addr = cs.raft.leader_addr()
            # A stale self-hint would loop the RPC back into our own
            # worker pool until it deadlocks — treat it as leaderless.
            if addr is None or addr == cs.rpc.addr:
                raise RPCError("no cluster leader")
            return cs.pool.call(addr, method, args, timeout_s=30.0)

        return call_with_retry(
            attempt,
            policy=cs.forward_retry,
            retry_if=_is_leaderless_error,
            label=method,
        )


class OperatorEndpoint(_Forwarder):
    """Reference: nomad/operator_endpoint.go + helper/snapshot — state
    snapshot save/restore and raft introspection for operators."""

    def snapshot_save(self, args):
        # any server can serve its own (possibly slightly stale) state
        return {"snapshot": self.cs.server.state.serialize()}

    def snapshot_restore(self, args):
        return self._forward(
            "Operator.snapshot_restore",
            args,
            lambda a: self.cs.server.raft_apply("snapshot_restore", a["data"]),
        )

    def force_gc(self, args):
        return self._forward(
            "Operator.force_gc",
            args,
            lambda a: self.cs.server.force_gc(),
        )

    def autopilot_get_config(self, args):
        """Reference operator_endpoint.go AutopilotGetConfiguration
        (the OSS-relevant knob: dead-server cleanup). Raft-replicated:
        every replica reads its own consistent copy and the setting
        survives failover."""
        return self.cs.autopilot_config()

    def autopilot_set_config(self, args):
        def apply(a):
            cfg = a.get("config") or {}
            cur = self.cs.autopilot_config()
            if "CleanupDeadServers" in cfg:
                cur["CleanupDeadServers"] = bool(
                    cfg["CleanupDeadServers"]
                )
            self.cs.server.raft_apply("operator_config_upsert",
                                      ("autopilot", cur))
            return {"Updated": True}

        return self._forward(
            "Operator.autopilot_set_config", args, apply
        )

    def force_leave(self, args):
        return self.cs.force_leave(args["member_id"])

    def scheduler_get_config(self, args):
        def local(a):
            return self._scheduler_config_payload()

        return self._forward("Operator.scheduler_get_config", args, local)

    def _scheduler_config_payload(self):
        c = self.cs.server.scheduler_config
        return {
            "SchedulerAlgorithm": c.algorithm,
            "PreemptionConfig": {
                "ServiceSchedulerEnabled": c.preemption_service,
                "BatchSchedulerEnabled": c.preemption_batch,
                "SystemSchedulerEnabled": c.preemption_system,
                "SysBatchSchedulerEnabled": c.preemption_sysbatch,
            },
            "MemoryOversubscriptionEnabled": c.memory_oversubscription,
            "Backend": c.backend,
        }

    def scheduler_set_config(self, args):
        """Mutate the live scheduler knobs (reference
        operator_endpoint.go SchedulerSetConfiguration; the reference
        raft-replicates the config — here it is leader-local operator
        state, re-set after failover)."""

        def apply(a):
            cfg = a.get("config") or {}
            c = self.cs.server.scheduler_config
            if "SchedulerAlgorithm" in cfg:
                algo = cfg["SchedulerAlgorithm"]
                if algo not in ("binpack", "spread"):
                    raise ValueError(f"unknown algorithm {algo!r}")
                c.algorithm = algo
            pre = cfg.get("PreemptionConfig") or {}
            for key, attr in (
                ("ServiceSchedulerEnabled", "preemption_service"),
                ("BatchSchedulerEnabled", "preemption_batch"),
                ("SystemSchedulerEnabled", "preemption_system"),
                ("SysBatchSchedulerEnabled", "preemption_sysbatch"),
            ):
                if key in pre:
                    setattr(c, attr, bool(pre[key]))
            if "MemoryOversubscriptionEnabled" in cfg:
                c.memory_oversubscription = bool(
                    cfg["MemoryOversubscriptionEnabled"]
                )
            return {"Updated": True}

        return self._forward("Operator.scheduler_set_config", args, apply)

    def raft_remove_peer(self, args):
        """Force-remove a raft peer (reference operator_endpoint.go
        RaftRemovePeerByID — recovering a cluster whose dead member
        can't leave gracefully)."""
        return self._forward(
            "Operator.raft_remove_peer",
            args,
            lambda a: self.cs.raft.remove_peer(a["peer_id"]),
        )

    def raft_configuration(self, args):
        out = [
            {
                "id": self.cs.node_id,
                "address": list(self.cs.rpc.addr),
                "leader": self.cs.raft.is_leader(),
            }
        ]
        with self.cs.raft._lock:
            peers = dict(self.cs.raft.peers)
        leader = self.cs.raft.leader_id
        for pid, addr in peers.items():
            out.append(
                {"id": pid, "address": list(addr), "leader": pid == leader}
            )
        return out


class JobEndpoint(_Forwarder):
    def register(self, args):
        return self._forward(
            "Job.register", args, lambda a: self.cs.server.job_register(a["job"])
        )

    def deregister(self, args):
        return self._forward(
            "Job.deregister",
            args,
            lambda a: self.cs.server.job_deregister(
                a["namespace"], a["job_id"], a.get("purge", False)
            ),
        )

    def get(self, args):
        return self.cs.server.state.job_by_id(args["namespace"], args["job_id"])

    def list(self, args):
        return self.cs.server.state.jobs(args.get("namespace"))

    def allocs(self, args):
        return self.cs.server.state.allocs_by_job(
            args["namespace"], args["job_id"]
        )

    def summary(self, args):
        return self.cs.server.state.job_summary_by_id(
            args["namespace"], args["job_id"]
        )

    def evals(self, args):
        return self.cs.server.state.evals_by_job(
            args["namespace"], args["job_id"]
        )

    def versions(self, args):
        return self.cs.server.state.job_versions(
            args["namespace"], args["job_id"]
        )

    def revert(self, args):
        return self._forward(
            "Job.revert",
            args,
            lambda a: self.cs.server.job_revert(
                a["namespace"], a["job_id"], a["version"]
            ),
        )

    def dispatch(self, args):
        return self._forward(
            "Job.dispatch",
            args,
            lambda a: self.cs.server.job_dispatch(
                a["namespace"],
                a["job_id"],
                payload=a.get("payload") or b"",
                meta=a.get("meta") or {},
            ),
        )

    def periodic_force(self, args):
        def local(a):
            # front-door admission: force_launch mints a child job +
            # eval directly (not via job_register, whose own guard
            # covers register/scale/revert) — the periodic dispatcher's
            # internal timer path stays unguarded on purpose
            self.cs.server.check_eval_admission(a["namespace"])
            return self.cs.server.periodic.force_launch(
                a["namespace"], a["job_id"]
            )

        return self._forward("Job.periodic_force", args, local)

    def scale_status(self, args):
        """Group-level desired/placed/running counts (reference
        Job.ScaleStatus)."""
        st = self.cs.server.state
        job = st.job_by_id(args["namespace"], args["job_id"])
        if job is None:
            return None
        allocs = st.allocs_by_job(args["namespace"], args["job_id"])
        policies = {
            p.group: p
            for p in st.scaling_policies_by_job(
                args["namespace"], args["job_id"]
            )
        }
        groups = {}
        for tg in job.task_groups:
            live = [
                a
                for a in allocs
                if a.task_group == tg.name and not a.terminal_status()
            ]
            entry = {
                "Desired": tg.count,
                "Running": sum(
                    1 for a in live if a.client_status == "running"
                ),
                "Placed": len(live),
            }
            pol = policies.get(tg.name)
            if pol is not None:
                entry["ScalingPolicy"] = {
                    "ID": pol.id, "Min": pol.min, "Max": pol.max,
                    "Enabled": pol.enabled,
                }
            groups[tg.name] = entry
        return {
            "JobID": job.id,
            "JobStopped": job.stop,
            "TaskGroups": groups,
            # newest-first scale-event journal per group (reference
            # JobScaleStatus — `nomad job scaling-events` reads this)
            "ScalingEvents": st.scaling_events(
                args["namespace"], args["job_id"]
            ),
        }

    def evaluate(self, args):
        return self._forward(
            "Job.evaluate",
            args,
            lambda a: self.cs.server.job_force_evaluate(
                a["namespace"], a["job_id"]
            ),
        )

    def deployments(self, args):
        return self.cs.server.state.deployments_by_job(
            args["namespace"], args["job_id"]
        )

    def scale(self, args):
        return self._forward(
            "Job.scale",
            args,
            lambda a: self.cs.server.job_scale(
                a["namespace"], a["job_id"], a["group"], a["count"],
                a.get("message", ""),
            ),
        )

    def plan(self, args):
        # Dry-run: leader-forwarded so the plan sees the freshest state,
        # but nothing is committed (reference job_endpoint.go:521).
        return self._forward(
            "Job.plan",
            args,
            lambda a: self.cs.server.job_plan(
                a["job"], diff=a.get("diff", True)
            ),
        )


class SearchEndpoint(_Forwarder):
    """Reference: nomad/search_endpoint.go."""

    def prefix(self, args):
        from .search import prefix_search

        return prefix_search(
            self.cs.server.state,
            args.get("prefix", ""),
            args.get("context", "all"),
            args.get("namespace", "default"),
        )

    def fuzzy(self, args):
        from .search import fuzzy_search

        return fuzzy_search(
            self.cs.server.state,
            args.get("text", ""),
            args.get("context", "all"),
            args.get("namespace", "default"),
        )


class NamespaceEndpoint(_Forwarder):
    """Reference: nomad/namespace_endpoint.go."""

    def upsert(self, args):
        return self._forward(
            "Namespace.upsert",
            args,
            lambda a: self.cs.server.namespace_upsert(a["namespace"]),
        )

    def delete(self, args):
        return self._forward(
            "Namespace.delete",
            args,
            lambda a: self.cs.server.namespace_delete(a["name"]),
        )

    def get(self, args):
        return self.cs.server.state.namespace_by_name(args["name"])

    def list(self, args):
        return sorted(
            self.cs.server.state.namespaces(), key=lambda n: n.name
        )


class VolumeEndpoint(_Forwarder):
    """Reference: nomad/csi_endpoint.go reshaped for host volumes."""

    def register(self, args):
        return self._forward(
            "Volume.register",
            args,
            lambda a: self.cs.server.volume_register(a["volume"]),
        )

    def deregister(self, args):
        return self._forward(
            "Volume.deregister",
            args,
            lambda a: self.cs.server.volume_deregister(
                a["namespace"], a["volume_id"]
            ),
        )

    def get(self, args):
        return self.cs.server.state.volume_by_id(
            args["namespace"], args["volume_id"]
        )

    def list(self, args):
        return self.cs.server.state.volumes(args.get("namespace"))

    def for_alloc(self, args):
        return self.cs.server.state.volumes_for_alloc(args["alloc_id"])

    def create(self, args):
        """Provision through a controller plugin then register
        (reference csi_endpoint.go Create → ClientCSI controller RPC on
        a plugin-bearing node)."""

        def local(a):
            vol = a["volume"]
            # validate BEFORE provisioning: a rejected register after
            # the controller call would orphan the external storage
            self.cs.server.validate_volume(vol)
            if vol.plugin_id == "":
                raise ValueError("csi volume requires plugin_id")
            existing = self.cs.server.state.volume_by_id(
                vol.namespace, vol.id
            )
            if existing is not None:
                raise ValueError(
                    f"volume {vol.id} already exists (external id "
                    f"{existing.external_id!r}); delete it first"
                )
            out = self.cs.csi_controller_roundtrip(
                vol.plugin_id,
                "CSI.create",
                {"name": vol.name or vol.id,
                 "params": dict(vol.context or {})},
            )
            vol = vol.copy()
            vol.type = "csi"
            vol.external_id = out.get("external_id", "")
            ctx = out.get("context") or {}
            vol.context = {**(vol.context or {}), **ctx}
            self.cs.server.volume_register(vol)
            return self.cs.server.state.volume_by_id(
                vol.namespace, vol.id
            )

        return self._forward("Volume.create", args, local)

    def delete(self, args):
        """Deregister then deprovision via the controller plugin
        (reference csi_endpoint.go Delete)."""

        def local(a):
            ns, vol_id = a["namespace"], a["volume_id"]
            vol = self.cs.server.state.volume_by_id(ns, vol_id)
            if vol is None:
                raise KeyError(f"volume {vol_id} not found")
            if vol.claims:
                raise ValueError(
                    f"volume {vol_id} has {len(vol.claims)} active claims"
                )
            # Deprovision BEFORE dropping the record: a controller
            # failure here leaves the record in place so the operator
            # can retry — the reverse order would orphan the external
            # storage forever (the record with its external_id is the
            # only handle we have on it).
            if vol.plugin_id and vol.external_id:
                self.cs.csi_controller_roundtrip(
                    vol.plugin_id,
                    "CSI.delete",
                    {"external_id": vol.external_id},
                )
            self.cs.server.volume_deregister(ns, vol_id)
            return None

        return self._forward("Volume.delete", args, local)

    def plugins(self, args):
        return self.cs.server.state.csi_plugins()

    def detach(self, args):
        """Operator escape hatch for a wedged attachment (reference
        csi_endpoint.go Unpublish / `nomad volume detach`): release the
        volume's claims held by allocs on one node and tell the
        controller plugin to unpublish it there."""

        def local(a):
            ns, vol_id, node_id = (
                a["namespace"], a["volume_id"], a["node_id"]
            )
            vol = self.cs.server.state.volume_by_id(ns, vol_id)
            if vol is None:
                raise KeyError(f"volume {vol_id} not found")
            alloc_ids = [
                c.alloc_id
                for c in vol.claims.values()
                if c.node_id == node_id
            ]
            if alloc_ids:
                # scoped: these allocs may hold legitimate claims on
                # OTHER volumes — only this volume's claims release
                self.cs.server.raft_apply(
                    "volume_claim_release",
                    {
                        "namespace": ns,
                        "volume_id": vol_id,
                        "alloc_ids": alloc_ids,
                    },
                )
            if vol.plugin_id and vol.external_id:
                self.cs.csi_controller_roundtrip(
                    vol.plugin_id,
                    "CSI.controller_unpublish",
                    {
                        "volume_id": vol.id,
                        "external_id": vol.external_id,
                        "node_id": node_id,
                    },
                )
            return {"released_claims": len(alloc_ids)}

        return self._forward("Volume.detach", args, local)

    def snapshot_create(self, args):
        """Point-in-time snapshot of a registered CSI volume (reference
        csi_endpoint.go CreateSnapshot → controller RPC)."""

        def local(a):
            ns, vol_id = a["namespace"], a["volume_id"]
            vol = self.cs.server.state.volume_by_id(ns, vol_id)
            if vol is None:
                raise KeyError(f"volume {vol_id} not found")
            if not vol.plugin_id or not vol.external_id:
                raise ValueError(
                    f"volume {vol_id} is not a provisioned CSI volume"
                )
            out = self.cs.csi_controller_roundtrip(
                vol.plugin_id,
                "CSI.create_snapshot",
                {
                    "external_id": vol.external_id,
                    "name": a.get("name") or vol_id,
                    "params": dict(vol.context or {}),
                },
            )
            # documented shape only — the roundtrip's transport "ok"
            # key must not become accidental API contract
            return {
                k: out.get(k)
                for k in (
                    "snapshot_id", "source_external_id", "size_mb",
                    "create_time_ns", "ready",
                )
            }

        return self._forward("Volume.snapshot_create", args, local)

    def snapshot_delete(self, args):
        def local(a):
            self.cs.csi_controller_roundtrip(
                a["plugin_id"],
                "CSI.delete_snapshot",
                {"snapshot_id": a["snapshot_id"]},
            )
            return None

        return self._forward("Volume.snapshot_delete", args, local)

    def snapshot_list(self, args):
        def local(a):
            out = self.cs.csi_controller_roundtrip(
                a["plugin_id"], "CSI.list_snapshots", {}
            )
            return out.get("snapshots", [])

        return self._forward("Volume.snapshot_list", args, local)


class SecretsEndpoint(_Forwarder):
    """Embedded secrets store + task-token derivation (the Vault-analog
    server side; reference nomad/vault.go + client/vaultclient)."""

    def upsert(self, args):
        return self._forward(
            "Secrets.upsert",
            args,
            lambda a: self.cs.server.secret_upsert(a["entry"]),
        )

    def delete(self, args):
        return self._forward(
            "Secrets.delete",
            args,
            lambda a: self.cs.server.secret_delete(
                a["namespace"], a["path"]
            ),
        )

    def read(self, args):
        ns = args.get("namespace", "default")
        # Task template reads authenticate with the task's DERIVED token
        # (the consul-template-with-vault-token model): when enforcement
        # is on, the token's policies must grant read-secret in the
        # namespace — a task without a vault stanza has no token and
        # reads nothing.
        if self.cs.acl_enforce:
            try:
                acl = self.cs.server.resolve_token(args.get("token", ""))
            except PermissionError as e:
                raise PermissionError(f"secret read: {e}") from None
            if acl is None:
                raise PermissionError("secret read: missing token")
            if not acl.is_management() and not acl.allow_namespace_op(
                ns, "read-secret"
            ):
                raise PermissionError(
                    "secret read: missing 'read-secret' capability"
                )
        return self.cs.server.state.secret_by_path(ns, args["path"])

    def list(self, args):
        # redact values in listings — only `read` of a named path
        # returns items
        out = []
        for e in self.cs.server.state.secrets(args.get("namespace")):
            out.append({
                "path": e.path,
                "namespace": e.namespace,
                "keys": sorted(e.items),
                "modify_index": e.modify_index,
            })
        return out

    def derive_token(self, args):
        return self._forward(
            "Secrets.derive_token",
            args,
            lambda a: self.cs.server.derive_task_token(
                a["alloc_id"], a["task_name"]
            ),
        )

    def renew_token(self, args):
        return self._forward(
            "Secrets.renew_token",
            args,
            lambda a: self.cs.server.renew_task_token(a["accessor_id"]),
        )

    def revoke_token(self, args):
        return self._forward(
            "Secrets.revoke_token",
            args,
            lambda a: self.cs.server.acl_token_delete([a["accessor_id"]]),
        )


class ServiceEndpoint(_Forwarder):
    """Native service discovery (reference:
    nomad/service_registration_endpoint.go)."""

    def register(self, args):
        return self._forward(
            "Service.register",
            args,
            lambda a: self.cs.server.services_register(a["regs"]),
        )

    def deregister_alloc(self, args):
        return self._forward(
            "Service.deregister_alloc",
            args,
            lambda a: self.cs.server.services_deregister_alloc(a["alloc_id"]),
        )

    def deregister(self, args):
        return self._forward(
            "Service.deregister",
            args,
            lambda a: self.cs.server.services_deregister(a["ids"]),
        )

    def list(self, args):
        return self.cs.server.state.service_names(args.get("namespace"))

    def get(self, args):
        return self.cs.server.state.service_registrations(
            args.get("namespace", "default"), args["name"]
        )


class NodeEndpoint(_Forwarder):
    def register(self, args):
        return self._forward(
            "Node.register", args, lambda a: self.cs.server.node_register(a["node"])
        )

    def heartbeat(self, args):
        return self._forward(
            "Node.heartbeat",
            args,
            lambda a: self.cs.server.node_heartbeat(a["node_id"]),
        )

    def update_status(self, args):
        return self._forward(
            "Node.update_status",
            args,
            lambda a: self.cs.server.node_update_status(a["node_id"], a["status"]),
        )

    def update_drain(self, args):
        return self._forward(
            "Node.update_drain",
            args,
            lambda a: self.cs.server.node_update_drain(
                a["node_id"], a.get("drain"), a.get("mark_eligible", False)
            ),
        )

    def update_eligibility(self, args):
        return self._forward(
            "Node.update_eligibility",
            args,
            lambda a: self.cs.server.node_update_eligibility(
                a["node_id"], a["eligibility"]
            ),
        )

    def get_client_allocs(self, args):
        # Blocking query served from the local replica: alloc writes reach
        # followers via raft, waking the same watch channels.
        allocs, index = self.cs.server.get_client_allocs(
            args["node_id"],
            args.get("min_index", 0),
            args.get("timeout_s", 5.0),
        )
        return {"allocs": allocs, "index": index}

    def update_allocs(self, args):
        return self._forward(
            "Node.update_allocs",
            args,
            lambda a: self.cs.server.update_allocs_from_client(a["allocs"]),
        )

    def get(self, args):
        return self.cs.server.state.node_by_id(args["node_id"])

    def list(self, args):
        return self.cs.server.state.nodes()

    def purge(self, args):
        return self._forward(
            "Node.purge",
            args,
            lambda a: self.cs.server.raft_apply("node_deregister", a["node_id"]),
        )


class EvalEndpoint(_Forwarder):
    def get(self, args):
        return self.cs.server.state.eval_by_id(args["eval_id"])

    def delete(self, args):
        """Delete terminal evals (reference eval_endpoint.go Delete —
        1.4's operator eval cleanup). The terminal check lives HERE, on
        the leader, immediately before the apply — an HTTP-layer-only
        check would let any fabric caller (or a check-then-apply race)
        drop a pending eval from the broker."""

        def local(a):
            for eid in a["eval_ids"]:
                ev = self.cs.server.state.eval_by_id(eid)
                if ev is None:
                    raise KeyError(f"eval {eid} not found")
                if not ev.terminal_status():
                    raise ValueError(
                        f"eval {eid} is {ev.status}; only terminal "
                        f"evaluations can be deleted"
                    )
            return self.cs.server.raft_apply(
                "eval_delete", (a["eval_ids"], [])
            )

        return self._forward("Eval.delete", args, local)

    def allocs(self, args):
        return self.cs.server.state.allocs_by_eval(args["eval_id"])

    def list(self, args):
        return self.cs.server.state.evals()


class AllocEndpoint(_Forwarder):
    def get(self, args):
        return self.cs.server.state.alloc_by_id(args["alloc_id"])

    def list(self, args):
        return self.cs.server.state.allocs()

    def stop(self, args):
        def local(a):
            try:
                alloc = self.cs.find_alloc(a["alloc_id"])
            except LookupError as e:
                raise KeyError(str(e)) from None
            return self.cs.server.alloc_stop(alloc.id)

        return self._forward("Alloc.stop", args, local)

    def list_by_node(self, args):
        return self.cs.server.state.allocs_by_node(args["node_id"])

    def client_addr(self, args):
        """(alloc, 'host:port' of its node's client fabric) — the
        prev-alloc migrator's cross-node lookup."""
        st = self.cs.server.state
        alloc = st.alloc_by_id(args["alloc_id"])
        if alloc is None:
            return None, None
        node = st.node_by_id(alloc.node_id)
        addr = node.attributes.get("unique.client.rpc") if node else None
        return alloc, addr


class DeploymentEndpoint(_Forwarder):
    def get(self, args):
        return self.cs.server.state.deployment_by_id(args["deployment_id"])

    def list(self, args):
        return self.cs.server.state.deployments()

    def promote(self, args):
        return self._forward(
            "Deployment.promote",
            args,
            lambda a: self.cs.server.deployment_promote(
                a["deployment_id"], a.get("groups")
            ),
        )

    def pause(self, args):
        return self._forward(
            "Deployment.pause",
            args,
            lambda a: self.cs.server.deployment_pause(
                a["deployment_id"], a["pause"]
            ),
        )

    def fail(self, args):
        return self._forward(
            "Deployment.fail",
            args,
            lambda a: self.cs.server.deployment_fail(a["deployment_id"]),
        )


class ACLEndpoint(_Forwarder):
    def _forward_authoritative(self, method: str, args):
        """Replicated ACL state (policies, global tokens) is writable
        ONLY in the authoritative region — a write landed here would be
        reverted by the next replication poll. Forward it (reference
        acl_endpoint.go rewrites args.Region to AuthoritativeRegion).
        Returns None when THIS region is authoritative (or federation
        is unconfigured) and the caller should apply locally."""
        cs = self.cs
        if not cs.authoritative_region or cs.region == cs.authoritative_region:
            return None
        addr = cs.region_server(cs.authoritative_region)
        if addr is None:
            raise RPCError(
                f"authoritative region {cs.authoritative_region!r} "
                f"unreachable for replicated ACL write"
            )
        return lambda: cs.pool.call(addr, method, args, timeout_s=10.0)

    def bootstrap(self, args):
        return self._forward(
            "ACL.bootstrap", args, lambda a: self.cs.server.acl_bootstrap()
        )

    def policy_upsert(self, args):
        fwd = self._forward_authoritative("ACL.policy_upsert", args)
        if fwd is not None:
            return fwd()
        return self._forward(
            "ACL.policy_upsert",
            args,
            lambda a: self.cs.server.acl_policy_upsert(a["policies"]),
        )

    def policy_delete(self, args):
        fwd = self._forward_authoritative("ACL.policy_delete", args)
        if fwd is not None:
            return fwd()
        return self._forward(
            "ACL.policy_delete",
            args,
            lambda a: self.cs.server.acl_policy_delete(a["names"]),
        )

    def policy_get(self, args):
        return self.cs.server.state.acl_policy_by_name(args["name"])

    def policy_list(self, args):
        return self.cs.server.state.acl_policies()

    def token_create(self, args):
        # Global tokens are minted in the authoritative region and
        # replicate outward (reference acl_endpoint.go UpsertTokens
        # forwards globals to AuthoritativeRegion; leader.go:1423 pulls
        # them back). Local tokens stay region-local.
        token = args.get("token")
        stored_global = False
        if token is not None and token.accessor_id:
            stored = self.cs.server.state.acl_token_by_accessor(
                token.accessor_id
            )
            stored_global = stored is not None and stored.global_
        # forward when the token IS global or WAS global (a demotion to
        # local must land authoritatively too, or replication re-promotes
        # it here within one poll)
        if token is not None and (
            getattr(token, "global_", False) or stored_global
        ):
            fwd = self._forward_authoritative("ACL.token_create", args)
            if fwd is not None:
                return fwd()
        return self._forward(
            "ACL.token_create",
            args,
            lambda a: self.cs.server.acl_token_create(a["token"]),
        )

    def token_delete(self, args):
        # Global-token deletes must land in the authoritative region or
        # the replication poll resurrects the revoked secret here within
        # one interval. Split the batch: globals forward, locals apply.
        state = self.cs.server.state
        accessors = list(args.get("accessor_ids", []))
        global_ids = [
            aid
            for aid in accessors
            if (t := state.acl_token_by_accessor(aid)) is not None
            and t.global_
        ]
        if global_ids:
            fwd = self._forward_authoritative(
                "ACL.token_delete", {**args, "accessor_ids": global_ids}
            )
            if fwd is not None:
                fwd()
                accessors = [a for a in accessors if a not in global_ids]
                if not accessors:
                    return None
                args = {**args, "accessor_ids": accessors}
        return self._forward(
            "ACL.token_delete",
            args,
            lambda a: self.cs.server.acl_token_delete(a["accessor_ids"]),
        )

    def token_get(self, args):
        return self.cs.server.state.acl_token_by_accessor(args["accessor_id"])

    def replicate(self, args):
        """Server-to-server replication feed (reference ACL.ListPolicies /
        ACL.ListTokens driven by leader.go:1282,1423): full policy set +
        GLOBAL tokens WITH secrets, plus the acl table index so pollers
        no-op cheaply. Rides the server fabric only — the fabric's shared
        rpc secret/mTLS is the authorization boundary (the reference uses
        a replication token; external clients never see this surface
        because token_list redacts secrets)."""
        from ..state.store import TABLE_ACL_POLICIES, TABLE_ACL_TOKENS

        state = self.cs.server.state
        idx = state.table_index(TABLE_ACL_POLICIES, TABLE_ACL_TOKENS)
        if args.get("min_index") and idx <= args["min_index"]:
            return {"index": idx, "unchanged": True}
        return {
            "index": idx,
            "policies": state.acl_policies(),
            "tokens": [t for t in state.acl_tokens() if t.global_],
        }

    def token_list(self, args):
        # Secrets are never listed (reference redacts SecretID on list).
        out = []
        for t in self.cs.server.state.acl_tokens():
            c = t.copy()
            c.secret_id = ""
            out.append(c)
        return out


class ScalingEndpoint(_Forwarder):
    """Reference: nomad/scaling_endpoint.go."""

    def list_policies(self, args):
        return self.cs.server.state.scaling_policies(
            args.get("namespace")
        )

    def get_policy(self, args):
        return self.cs.server.state.scaling_policy_by_id(
            args["policy_id"]
        )


class SystemEndpoint(_Forwarder):
    """Reference: nomad/system_endpoint.go."""

    def reconcile_summaries(self, args):
        return self._forward(
            "System.reconcile_summaries",
            args,
            lambda a: self.cs.server.reconcile_job_summaries(),
        )


class StatusEndpoint(_Forwarder):
    def leader(self, args):
        addr = self.cs.raft.leader_addr()
        return {"leader": list(addr) if addr else None}

    def regions(self, args):
        """Distinct regions known via gossip (reference
        nomad/regions_endpoint.go — federation membership rides serf)."""
        regions = {self.cs.region}
        for m in self.cs.serf.members():
            r = (m.tags or {}).get("region")
            if r:
                regions.add(r)
        return sorted(regions)

    def peers(self, args):
        out = [
            {"id": self.cs.node_id, "addr": list(self.cs.rpc.addr)}
        ]
        with self.cs.raft._lock:  # applies mutate the dict in place
            peers = dict(self.cs.raft.peers)
        for pid, addr in peers.items():
            out.append({"id": pid, "addr": list(addr)})
        return out

    def ping(self, args):
        return "pong"

    def members(self, args):
        return [m.to_wire() for m in self.cs.serf.members()]

    def peer_telemetry(self, args):
        """One member's health/telemetry summary, answered LOCALLY
        (never forwarded — the caller is federating, and a forward
        would report the leader's numbers as ours). The leader-side
        aggregation in ClusterServer.cluster_health pulls this from
        every member with a bounded per-peer deadline."""
        top = int((args or {}).get("top", 5))
        return self.cs.peer_telemetry(top=top)


class ClusterServer:
    def __init__(
        self,
        node_id: str,
        peers: Optional[dict[str, tuple[str, int]]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        num_workers: int = 2,
        use_tpu_batch_worker: bool = False,
        enabled_schedulers=None,
        region: str = "global",
        bootstrap_expect: Optional[int] = None,
        rpc_secret="",  # str | rpc.keyring.Keyring (shared by the agent)
        data_dir: Optional[str] = None,
        acl_enforce: bool = False,
        authoritative_region: Optional[str] = None,
        acl_replication_interval_s: float = 0.5,
        tls=None,  # (server_ctx, client_ctx) from rpc.tls.fabric_contexts
        solver_pool_role: str = "",
        solver_pool_members=(),
        solver_pool_sync_interval_s: float = 2.0,
        blackbox_enabled: bool = True,
        incident_dir: Optional[str] = None,
        incident_max: int = 16,
        **raft_kw,
    ) -> None:
        self.node_id = node_id
        self.region = region
        self.acl_enforce = acl_enforce
        # Federated ACL replication (reference leader.go:1282,1423): a
        # region naming an authoritative region other than itself pulls
        # that region's policies + global tokens on its leader.
        self.authoritative_region = authoritative_region
        self.acl_replication_interval_s = acl_replication_interval_s
        self._acl_repl_stop: Optional[threading.Event] = None
        self.tls = tls
        # One keyring for this server's listener AND dialer (rpc/
        # keyring.py): a live rpc_secret rotation (Agent.reload /
        # ChaosCluster.rotate_secret) moves both sides together. The
        # agent passes its process-shared Keyring; a plain string gets
        # a private one.
        from ..rpc.keyring import ensure_keyring

        self.keyring = ensure_keyring(rpc_secret)
        self.rpc = RPCServer(
            host=host, port=port, secret=self.keyring,
            tls_context=tls[0] if tls else None,
        )
        self.pool = ConnPool(
            secret=self.keyring, tls_context=tls[1] if tls else None
        )
        # Fault-plane identity (faultplane.py): injected partitions
        # and response drops match on these labels. No-ops in production.
        self.pool.owner = node_id
        self.rpc.chaos_label = node_id
        # Per-source cost ledger (clusterobs.py): THIS server's own
        # instance — an in-process test cluster attributes per member,
        # and Status.peer_telemetry reports each member's own ledger.
        # Both dispatch paths feed it: the fabric socket
        # (RPCServer._dispatch) and in-process rpc_self below. The
        # bounded provider gauges ride the registry; per-source detail
        # stays in the ledger (cardinality stays fixed).
        self.source_ledger = clusterobs.SourceLedger()
        self.rpc.source_ledger = self.source_ledger
        self._source_provider = metrics.register_provider(
            "nomad.rpc.source", self.source_ledger.stats
        )
        self._started_monotonic = time.monotonic()
        # Leaderless-window retry budget for _Forwarder (retry.py) —
        # overridable per deployment (tests shrink it).
        self.forward_retry = FORWARD_POLICY
        # Per-namespace token buckets on the RPC front door (ratelimit
        # .py; disabled until limits{} config sets a rate). Charged in
        # _rpc_precheck for the eval-minting write verbs only — raft,
        # serf, heartbeats, and reads must never be throttled (a
        # throttled heartbeat marks live nodes down, amplifying the
        # overload this exists to contain). A follower charges its own
        # bucket before forwarding, the leader charges again on arrival:
        # per-server budgets, conservative under forwarding.
        from ..ratelimit import KeyedRateLimiter

        self.rpc_limiter = KeyedRateLimiter()
        # The node door (fleet-scale survival): Node.register is the ONE
        # node-originated verb that gets admission control. A reconnect
        # storm (partition heals, mass agent restart) is survivable if
        # registrations are paced — clients back off on 429/Retry-After
        # and re-register within their TTL — whereas an unpaced storm
        # stacks raft writes behind every live heartbeat. Heartbeats
        # themselves stay unthrottled (throttling them manufactures the
        # very down-marks the door exists to prevent).
        self.node_limiter = KeyedRateLimiter()
        self.server = Server(
            num_workers=num_workers,
            use_tpu_batch_worker=use_tpu_batch_worker,
            enabled_schedulers=enabled_schedulers,
        )
        # Wider timers than the raw RaftNode defaults: a full server stacks
        # scheduler workers, watchers, and client traffic onto the same
        # process, so heartbeat delivery jitter is much higher than in a
        # bare raft cluster (GIL contention).
        raft_kw.setdefault("heartbeat_ms", 100)
        raft_kw.setdefault("election_ms", 1000)
        # Static peer wiring (tests, fixed configs) bootstraps immediately;
        # gossip-discovered clusters wait for bootstrap_expect members
        # (reference server config bootstrap_expect + serf discovery).
        if bootstrap_expect is None:
            bootstrap_expect = len(peers) + 1 if peers else 1
        raft_kw.setdefault("bootstrap_expect", bootstrap_expect)
        self._bootstrap_expect = bootstrap_expect
        self._bootstrapped = bool(peers) or bootstrap_expect <= 1
        # Durable raft storage (reference: raft-boltdb + FSM snapshots,
        # nomad/server.go:1210): with a data_dir, term/vote/log/snapshot
        # survive a full-cluster restart.
        self.raft_store = None
        if data_dir:
            import os

            from .raft_store import RaftLogStore

            self.raft_store = RaftLogStore(
                os.path.join(data_dir, "server", "raft.db")
            )
            self.raft_store.chaos_label = node_id
        self.raft = RaftNode(
            node_id,
            self.server.fsm,
            self.pool,
            self.rpc.addr,
            peers or {},
            snapshot_fn=self.server.state.serialize,
            restore_fn=self.server.state.restore_from,
            on_leader_change=self._on_leader_change,
            store=self.raft_store,
            **raft_kw,
        )
        self.server.set_raft_applier(self._raft_apply, self._raft_apply_async)
        # Replay barrier for establish_leadership (server.py): broker
        # state must be rebuilt only from a store that has applied this
        # leader's own barrier entry — i.e. the full committed log, not
        # a mid-replay prefix (the duplicate-alloc window after a
        # full-cluster restart with leadership churn).
        self.server.replay_barrier = self._replay_barrier
        self.rpc.precheck = self._rpc_precheck
        self.rpc.register("Raft", self.raft.endpoint)
        for name, ep in (
            ("Job", JobEndpoint(self)),
            ("Node", NodeEndpoint(self)),
            ("Eval", EvalEndpoint(self)),
            ("Alloc", AllocEndpoint(self)),
            ("Volume", VolumeEndpoint(self)),
            ("Service", ServiceEndpoint(self)),
            ("Secrets", SecretsEndpoint(self)),
            ("Namespace", NamespaceEndpoint(self)),
            ("Search", SearchEndpoint(self)),
            ("Deployment", DeploymentEndpoint(self)),
            ("ACL", ACLEndpoint(self)),
            ("Status", StatusEndpoint(self)),
            ("System", SystemEndpoint(self)),
            ("Scaling", ScalingEndpoint(self)),
            ("Operator", OperatorEndpoint(self)),
        ):
            self.rpc.register(name, ep)
        # Streaming exec splice: API consumer ↔ this server ↔ the
        # alloc's client agent ↔ driver pty (reference streaming path,
        # SURVEY §3.5 — 4 process boundaries).
        self.rpc.register_stream("ClientExec.exec", self._handle_exec_stream)
        # Reverse-dial registry: NAT'd clients park connections here that
        # the server can open streams over when forward-dial fails
        # (reference nomad/client_rpc.go yamux session reuse).
        self._reverse_lock = threading.Lock()
        self._reverse: dict[str, list[tuple]] = {}
        self.rpc.register_stream(
            "ClientReverse.register", self._handle_reverse_register
        )
        # Gossip membership (reference setupSerf): server-role tagged,
        # events drive leader-side raft peer reconciliation.
        self.serf = Membership(
            node_id,
            self.rpc.addr,
            pool=self.pool,
            tags={"role": "server", "region": region},
            on_event=self._on_member_event,
        )
        self.rpc.register("Serf", self.serf.endpoint)
        # Solver-pool tier (server/solver_pool.py): membership hangs off
        # the serf ring above (tag solver=1); the endpoint serves warm
        # remote solves; the leader's TPU worker dispatches through the
        # tracker. Constructed AFTER serf so role="solver" can advertise
        # on the local member record before gossip starts.
        from .solver_pool import SolverPool

        self.solver_pool = SolverPool(
            self,
            role=solver_pool_role,
            members=solver_pool_members,
            sync_interval_s=solver_pool_sync_interval_s,
        )
        self.rpc.register("SolverPool", self.solver_pool.endpoint)
        if getattr(self.server, "tpu_worker", None) is not None:
            self.server.tpu_worker.solver_pool = self.solver_pool
        # Blackbox flight recorder (blackbox.py + blackbox_wire.py):
        # always-on journal pump + anomaly triggers + incident capture.
        # Owned here (not by the Agent) so bare ClusterServers — chaos
        # clusters included — are self-forensic. Incident bundles land
        # under data_dir/incidents unless a dir is configured; with
        # neither (dev mode), captures stay in the in-memory ledger.
        from .blackbox_wire import BlackboxWiring

        if incident_dir is None and data_dir:
            import os

            incident_dir = os.path.join(data_dir, "incidents")
        self.blackbox = BlackboxWiring(
            self,
            incident_dir=incident_dir or "",
            incident_max=incident_max,
            enabled=blackbox_enabled,
        )
        # Member events are handled on a dedicated reconciler thread:
        # add_peer/remove_peer block on raft commit (up to 10s with no
        # quorum), which must never stall the gossip probe loop.
        self._reconcile_q: "queue.Queue" = queue.Queue()
        self._reconciler = threading.Thread(
            target=self._reconcile_loop,
            name=f"reconcile-{node_id}",
            daemon=True,
        )
        self._reconciler.start()

    # -- cluster-scope observability (clusterobs.py) -------------------

    def peer_telemetry(self, top: int = 5) -> dict:
        """THIS member's health/telemetry summary — the per-server row
        of ``/v1/operator/cluster/health`` (autopilot-health-shaped:
        raft indices, broker/plan-queue depths, host CPU/RSS, and the
        per-source cost top-K). Reads live structures only; cheap
        enough for a poll loop."""
        raft = self.raft
        srv = self.server
        from .. import hostobs

        host = clusterobs.host_summary()
        prof = hostobs.profiler()
        host["profiler_running"] = prof.running()
        host["busy_seconds"] = round(prof.busy_ns / 1e9, 3)
        return {
            "id": self.node_id,
            "region": self.region,
            "addr": list(self.rpc.addr),
            "leader": self.is_leader(),
            "leader_id": raft.leader_id,
            "uptime_s": round(
                time.monotonic() - self._started_monotonic, 1
            ),
            "raft": {
                "state": raft.state,
                "term": raft.current_term,
                "commit_index": raft.commit_index,
                "applied_index": raft.last_applied,
                "last_index": raft.last_index,
            },
            "broker": srv.eval_broker.stats_snapshot(),
            "plan_queue_depth": srv.plan_queue.depth(),
            "host": host,
            "sources": self.source_ledger.snapshot(top=top),
        }

    def cluster_health(
        self, per_peer_timeout_s: float = 2.0, top: int = 5
    ) -> dict:
        """Leader-side telemetry federation: pull every known member's
        ``Status.peer_telemetry`` over the existing fabric, each under
        a bounded per-peer deadline, in parallel. A member that cannot
        answer in time is reported ``degraded`` with the error — the
        response NEVER hangs on a partitioned or dead peer, and healthy
        members are still aggregated (the autopilot-health shape). Any
        server may serve this; it needs no leadership."""
        t0 = time.perf_counter()
        per_peer_timeout_s = max(0.1, min(float(per_peer_timeout_s), 30.0))
        top = max(1, min(int(top), 50))
        with self.raft._lock:  # applies mutate the dict in place
            peers = {
                pid: tuple(a) for pid, a in self.raft.peers.items()
            }
        for m in self.serf.members():
            if m.id != self.node_id and (m.tags or {}).get(
                "role"
            ) == "server":
                peers.setdefault(m.id, tuple(m.addr))
        peers.pop(self.node_id, None)
        results: dict[str, dict] = {}
        local = self.peer_telemetry(top=top)
        local["status"] = "ok"
        results[self.node_id] = local

        def query(pid: str, addr: tuple) -> None:
            try:
                out = self.pool.call(
                    addr,
                    "Status.peer_telemetry",
                    {"top": top},
                    timeout_s=per_peer_timeout_s,
                    retries=0,
                )
                out["status"] = "ok"
                results[pid] = out  # GIL-atomic store
            except Exception as e:
                # never overwrite a success a racing attempt landed
                results.setdefault(
                    pid,
                    {
                        "id": pid,
                        "addr": list(addr),
                        "status": "degraded",
                        "error": f"{type(e).__name__}: {e}",
                    },
                )

        threads = []
        for pid, addr in peers.items():
            t = threading.Thread(
                target=query,
                args=(pid, addr),
                name=f"cluster-health-{pid}",
                daemon=True,
            )
            t.start()
            threads.append(t)
        # one shared deadline: peers are queried in PARALLEL, so the
        # whole federation costs one per-peer budget (+ slack), not N.
        # Stragglers are left to their daemon threads and reported
        # degraded — a hung peer must never hang the response.
        deadline = time.monotonic() + per_peer_timeout_s + 0.25
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        servers = []
        degraded = []
        fleet = {
            "broker_ready": 0,
            "broker_unacked": 0,
            "plan_queue_depth": 0,
            "cpu_seconds": 0.0,
            "rss_bytes": 0,
        }
        source_rows: list[dict] = []
        for pid in sorted(set(peers) | {self.node_id}):
            ent = results.get(pid)
            if ent is None:
                ent = {
                    "id": pid,
                    "addr": list(peers.get(pid, ())),
                    "status": "degraded",
                    "error": "peer deadline exceeded",
                }
            if ent.get("status") == "ok":
                broker = ent.get("broker") or {}
                fleet["broker_ready"] += int(
                    broker.get("total_ready", 0)
                )
                fleet["broker_unacked"] += int(
                    broker.get("total_unacked", 0)
                )
                fleet["plan_queue_depth"] += int(
                    ent.get("plan_queue_depth", 0)
                )
                host = ent.get("host") or {}
                fleet["cpu_seconds"] = round(
                    fleet["cpu_seconds"]
                    + float(host.get("cpu_seconds", 0.0)),
                    3,
                )
                fleet["rss_bytes"] += int(host.get("rss_bytes", 0))
                source_rows.extend(
                    (ent.get("sources") or {}).get("top", [])
                )
            else:
                degraded.append(pid)
            servers.append(ent)
        fleet["sources_top"] = clusterobs.merge_top_sources(
            source_rows, top=top
        )
        leader_id = next(
            (s["id"] for s in servers if s.get("leader")), None
        )
        out = {
            "region": self.region,
            "queried_by": self.node_id,
            "leader": leader_id,
            "per_peer_timeout_s": per_peer_timeout_s,
            "elapsed_s": round(time.perf_counter() - t0, 4),
            "healthy": len(servers) - len(degraded),
            "degraded": degraded,
            "servers": servers,
            "fleet": fleet,
        }
        metrics.observe(
            "nomad.cluster.health_seconds", time.perf_counter() - t0
        )
        metrics.set_gauge("nomad.cluster.members", float(len(servers)))
        metrics.set_gauge(
            "nomad.cluster.degraded", float(len(degraded))
        )
        if degraded:
            metrics.incr("nomad.cluster.peer_degraded", len(degraded))
        return out

    # -- wiring --------------------------------------------------------

    def autopilot_config(self) -> dict:
        cfg = self.server.state.operator_config("autopilot")
        return dict(cfg) if cfg else {"CleanupDeadServers": True}

    def force_leave(self, member_id: str) -> int:
        """Force a (presumed-dead) member out of gossip everywhere
        (reference `server force-leave` / serf RemoveFailedNode).
        Returns how many peers acknowledged."""
        target = next(
            (m for m in self.serf.members() if m.id == member_id), None
        )
        # Unknown locally ⇒ peers may hold it at any incarnation: use an
        # operator-override incarnation that outranks organic ones (a
        # force-left member is declared dead; it does not refute).
        inc = (target.incarnation + 1) if target else (1 << 30)
        self.serf.endpoint.leave(
            {"id": member_id, "incarnation": inc}
        )
        acked = 0
        for m in self.serf.members():
            if m.id in (member_id, self.node_id):
                continue
            try:
                accepted = self.pool.call(
                    tuple(m.addr), "Serf.leave",
                    {"id": member_id, "incarnation": inc},
                    timeout_s=3.0,
                )
            except Exception:
                continue
            if accepted:
                acked += 1
        return acked

    def csi_controller_roundtrip(
        self, plugin_id: str, verb: str, header: dict
    ) -> dict:
        """Run one controller verb on SOME node carrying a healthy
        controller-capable instance of the plugin (reference: the server
        routes controller RPCs to a random plugin-bearing client)."""
        candidates = []
        for node in self.server.state.nodes():
            info = node.csi_plugins.get(plugin_id)
            addr_s = node.attributes.get("unique.client.rpc", "")
            if (
                info
                and info.get("healthy")
                and info.get("controller")
                and addr_s
            ):
                host, _, port = addr_s.rpartition(":")
                candidates.append((host, int(port)))
        if not candidates:
            raise RPCError(
                f"no healthy controller for CSI plugin {plugin_id!r}"
            )
        import random

        last: Exception = RPCError("unreachable")
        for addr in random.sample(candidates, len(candidates)):
            try:
                session = self.pool.stream(
                    addr, verb, {"plugin_id": plugin_id, **header}
                )
            except (ConnectionError, OSError) as e:
                last = e
                continue
            try:
                msg = session.recv(timeout_s=30)
            finally:
                session.close()
            if msg.get("error"):
                raise RPCError(msg["error"])
            return msg
        raise RPCError(f"controller unreachable: {last}")

    def find_alloc(self, alloc_id: str):
        """Resolve an alloc by exact id or unique prefix — the single
        source of truth for id resolution (state only; raises
        LookupError with a human message)."""
        state = self.server.state
        alloc = state.alloc_by_id(alloc_id)
        if alloc is None:
            matches = [a for a in state.allocs() if a.id.startswith(alloc_id)]
            if len(matches) > 1:
                raise LookupError(f"alloc id prefix {alloc_id!r} ambiguous")
            alloc = matches[0] if matches else None
        if alloc is None:
            raise LookupError(f"allocation {alloc_id!r} not found")
        return alloc

    def find_alloc_client(self, alloc_id: str):
        """find_alloc plus the client agent's advertised streaming
        address (the HTTP fs handlers and the fabric exec splice)."""
        state = self.server.state
        alloc = self.find_alloc(alloc_id)
        node = state.node_by_id(alloc.node_id)
        addr_s = (node.attributes.get("unique.client.rpc", "") if node else "")
        if not addr_s:
            raise LookupError(
                "allocation's node does not advertise a client endpoint"
            )
        host, _, port = addr_s.rpartition(":")
        return alloc, (host, int(port))

    def _handle_reverse_register(self, session, header: dict) -> None:
        """Park a client-initiated connection until a relay consumes it.

        The dispatch thread owns the socket and closes it on return, so
        while parked it polls the socket for liveness: a readable socket
        before the entry is CLAIMED means the client hung up (it sends
        nothing while parked) — prune the entry instead of leaking a
        thread + fd per reconnect of a flapping client. Once claimed, the
        handler waits for the relay's close (done)."""
        import select as _select
        import threading as _t

        node_id = header.get("node_id", "")
        if not node_id:
            session.send({"error": "node_id required"})
            return
        entry = {
            "session": session,
            "claimed": _t.Event(),
            "done": _t.Event(),
        }
        with self._reverse_lock:
            self._reverse.setdefault(node_id, []).append(entry)
        sock = session._sock
        while not entry["claimed"].is_set():
            try:
                readable, _, _ = _select.select([sock], [], [], 0.5)
            except (OSError, ValueError):
                readable = [sock]
            if entry["claimed"].is_set():
                break  # readable bytes belong to the consumer's exchange
            if readable:
                # EOF (or protocol violation) while parked: dead client
                with self._reverse_lock:
                    stack = self._reverse.get(node_id)
                    if stack and entry in stack:
                        stack.remove(entry)
                        if not stack:
                            del self._reverse[node_id]
                    elif entry["claimed"].is_set():
                        break  # consumer raced us; let it run
                session.close()
                return
        entry["done"].wait()

    def take_reverse_session(self, node_id: str, method: str, header: dict):
        """Open a stream over a connection the client dialed (the NAT
        fallback). Returns a ready session or None when the node has no
        parked connections on THIS server. Dead parked sessions (client
        went away) are skimmed off until one answers."""
        while True:
            with self._reverse_lock:
                stack = self._reverse.get(node_id)
                if not stack:
                    return None
                entry = stack.pop()
                if not stack:
                    del self._reverse[node_id]
                # claim under the lock: the parker's liveness poll must
                # not mistake the upcoming ack bytes for a dead client
                entry["claimed"].set()
            session, done = entry["session"], entry["done"]
            hdr = dict(header)
            hdr["method"] = method
            try:
                session.send(hdr)
                ack = session.recv(timeout_s=10)
            except (ConnectionError, OSError, TimeoutError):
                done.set()
                session.close()
                continue
            if not ack.get("ok"):
                done.set()
                session.close()
                if ack.get("error"):
                    raise RPCError(ack["error"])
                continue
            orig_close = session.close

            def tracked_close(done=done, orig_close=orig_close):
                done.set()
                orig_close()

            session.close = tracked_close
            return session

    def _close_reverse_sessions(self) -> None:
        with self._reverse_lock:
            parked = [
                entry for stack in self._reverse.values() for entry in stack
            ]
            self._reverse.clear()
        for entry in parked:
            entry["claimed"].set()
            entry["done"].set()
            entry["session"].close()

    def _handle_exec_stream(self, session, header: dict) -> None:
        """Splice an exec session through to the alloc's client agent."""
        down = None
        try:
            try:
                alloc, addr = self.find_alloc_client(header.get("alloc_id", ""))
            except LookupError as e:
                session.send({"error": str(e)})
                return
            # ACL: exec grants a shell inside the task — when enforcement
            # is on, require alloc-exec on the alloc's namespace
            # (reference nomad/client_alloc_endpoint.go exec).
            if self.acl_enforce:
                try:
                    acl = self.server.resolve_token(header.get("token", ""))
                except PermissionError:
                    session.send({"error": "ACL token not found"})
                    return
                if acl is None:
                    session.send({"error": "missing ACL token"})
                    return
                if not acl.is_management() and not acl.allow_namespace_op(
                    alloc.namespace, "alloc-exec"
                ):
                    session.send(
                        {"error": "missing 'alloc-exec' capability"}
                    )
                    return
            hdr = dict(header)
            hdr.pop("token", None)
            hdr["alloc_id"] = alloc.id
            try:
                down = self.pool.stream(addr, "Exec.exec", hdr)
            except (ConnectionError, OSError) as e:
                # same NAT fallback as the fs/logs relay
                down = self.take_reverse_session(
                    alloc.node_id, "Exec.exec", hdr
                )
                if down is None:
                    session.send(
                        {"error": f"client agent unreachable: {e}"}
                    )
                    return

            done = threading.Event()

            def pump_down_to_up() -> None:
                try:
                    while True:
                        msg = down.recv(timeout_s=None)
                        session.send(msg)
                        if msg.get("eof") or msg.get("error"):
                            break
                except (ConnectionError, OSError):
                    pass
                finally:
                    done.set()

            t = threading.Thread(
                target=pump_down_to_up, name="exec-stream-down",
                daemon=True,
            )
            t.start()
            while not done.is_set():
                try:
                    msg = session.recv(timeout_s=0.5)
                except TimeoutError:
                    continue
                except (ConnectionError, OSError):
                    break
                try:
                    down.send(msg)
                except (ConnectionError, OSError):
                    break
                if msg.get("eof"):
                    break
            done.wait(timeout=5)
        except (ConnectionError, OSError):
            pass
        finally:
            if down is not None:
                down.close()
            session.close()

    def _replay_barrier(self) -> bool:
        """Wait for local replay of this leadership's barrier entry, as
        long as we HOLD the leadership (a slow replay under load keeps
        waiting; a depose aborts immediately so the queued revoke runs)."""
        while not self.raft.wait_for_replay(timeout_s=5.0):
            if not self.raft.is_leader() or self.raft._stop.is_set():
                return False
        return True

    def _raft_apply(self, msg_type: str, payload) -> int:
        return self.raft.apply(msg_type, payload)

    def _raft_apply_async(self, msg_type: str, payload):
        index, term = self.raft.apply_submit(msg_type, payload)
        return index, (lambda: self.raft.apply_wait(index, term))

    def _on_leader_change(self, is_leader: bool) -> None:
        # journal the edge BEFORE acting on it: a revoke that hangs in
        # establish/revoke teardown still leaves its flight-recorder
        # trace, and the leader-churn trigger counts these rows
        blackbox.record(
            blackbox.KIND_LEADERSHIP,
            f"node:{self.node_id}",
            transition="establish" if is_leader else "revoke",
            term=self.raft.current_term,
            rel=[f"node:{self.node_id}"],
        )
        if is_leader:
            logger.info("%s: establishing leadership", self.node_id)
            self.server.establish_leadership()
            if (
                self.authoritative_region
                and self.authoritative_region != self.region
                and self._acl_repl_stop is None
            ):
                self._acl_repl_stop = threading.Event()
                t = threading.Thread(
                    target=self._acl_replication_loop,
                    args=(self._acl_repl_stop,),
                    name=f"acl-repl-{self.node_id}",
                    daemon=True,
                )
                t.start()
        else:
            logger.info("%s: revoking leadership", self.node_id)
            if self._acl_repl_stop is not None:
                self._acl_repl_stop.set()
                self._acl_repl_stop = None
            # Abort in-flight pool dispatches BEFORE stopping the worker:
            # revoke_leadership joins the commit stage, whose finish()
            # may be blocked on a remote solve — the abort resolves it
            # promptly and the batch NACKS (redelivers on the new
            # leader) instead of dropping or stalling the revoke.
            self.solver_pool.abort_inflight()
            self.server.revoke_leadership()

    def _acl_replication_loop(self, stop: threading.Event) -> None:
        """Leader-only puller in a NON-authoritative region: mirror the
        authoritative region's policies and global tokens into this
        region's raft (reference replicateACLPolicies leader.go:1282 +
        replicateACLTokens leader.go:1423). Local (non-global) tokens in
        this region are never touched; policies converge to the
        authoritative set exactly."""
        last_index = 0
        while not stop.wait(self.acl_replication_interval_s):
            addr = self.region_server(self.authoritative_region)
            if addr is None:
                continue  # authoritative region not gossip-visible yet
            try:
                feed = self.pool.call(
                    addr, "ACL.replicate", {"min_index": last_index},
                    timeout_s=10.0,
                )
            except Exception:
                continue  # transient fabric failure: retry next tick
            if feed.get("unchanged"):
                continue
            try:
                self._acl_apply_feed(feed)
                last_index = feed["index"]
            except NotLeaderError:
                return  # deposed mid-apply; the new leader re-pulls
            except Exception:
                # a transient apply failure (raft commit timeout under
                # load) must not kill the daemon — replication would
                # silently stop until the next leadership change
                logger.exception(
                    "%s: acl replication apply failed; retrying",
                    self.node_id,
                )

    def _acl_apply_feed(self, feed: dict) -> None:
        state = self.server.state
        want_pols = {p.name: p for p in feed.get("policies", [])}
        have_pols = {p.name: p for p in state.acl_policies()}
        upserts = [
            p
            for name, p in want_pols.items()
            if name not in have_pols
            or have_pols[name].rules != p.rules
            or have_pols[name].description != p.description
        ]
        deletes = [n for n in have_pols if n not in want_pols]
        if upserts:
            self.server.raft_apply(
                "acl_policy_upsert", [p.copy() for p in upserts]
            )
        if deletes:
            self.server.raft_apply("acl_policy_delete", deletes)
        want_toks = {t.accessor_id: t for t in feed.get("tokens", [])}
        have_toks = {
            t.accessor_id: t for t in state.acl_tokens() if t.global_
        }
        tok_up = [
            t
            for aid, t in want_toks.items()
            if aid not in have_toks
            or have_toks[aid].secret_id != t.secret_id
            or have_toks[aid].policies != t.policies
            or have_toks[aid].type != t.type
            or have_toks[aid].expiration_time_ns != t.expiration_time_ns
        ]
        tok_del = [aid for aid in have_toks if aid not in want_toks]
        if tok_up:
            self.server.raft_apply(
                "acl_token_upsert", [t.copy() for t in tok_up]
            )
        if tok_del:
            self.server.raft_apply("acl_token_delete", tok_del)

    @property
    def addr(self) -> tuple[str, int]:
        return self.rpc.addr

    def rpc_self(self, method: str, args):
        """In-process RPC dispatch (no socket hop): runs the endpoint
        locally, which itself forwards to the leader when needed — the
        reference's server.RPC fast path. A request naming another
        REGION forwards to a server there first (nomad/rpc.go
        forwardRegion via serf WAN membership)."""
        region = args.get("region") if isinstance(args, dict) else None
        if region and region != self.region:
            addr = self.region_server(region)
            if addr is None:
                raise RPCError(f"no known servers in region {region!r}")
            return self.pool.call(addr, method, args, timeout_s=30.0)
        # Per-source attribution for the in-process door too (HTTP
        # routes, co-located client agents): same ledger + thread-source
        # registry as the fabric path in RPCServer._dispatch. The outer
        # source is saved/restored — a handler that internally re-enters
        # rpc_self must not lose its caller's attribution.
        sources = clusterobs.thread_sources()
        tid = threading.get_ident()
        prev = sources.get(tid)
        source = clusterobs.source_of("", args)
        sources[tid] = source
        t0 = time.perf_counter()
        try:
            return self.rpc.dispatch_local(method, args)
        finally:
            if prev is None:
                sources.pop(tid, None)
            else:
                sources[tid] = prev
            self.source_ledger.record(
                source, method, time.perf_counter() - t0
            )

    # The write verbs the per-namespace RPC rate limit covers: every
    # eval-minting mutation a client can drive in a loop. Deliberately
    # absent: deregister/stop (shedding a stop strands capacity),
    # node/heartbeat traffic, raft/serf internals, and all reads.
    _RATE_LIMITED_METHODS = frozenset({
        "Job.register",
        "Job.scale",
        "Job.evaluate",
        "Job.dispatch",
        "Job.revert",
        "Job.periodic_force",
    })

    def set_rate_limits(self, rpc_rate: float, rpc_burst: float = 0.0) -> None:
        """Configure (or SIGHUP-reconfigure) the per-namespace RPC
        front-door token buckets. rate <= 0 disables."""
        self.rpc_limiter.configure(rpc_rate, rpc_burst)

    def set_node_register_limit(
        self, rate: float, burst: float = 0.0
    ) -> None:
        """Configure (or SIGHUP-reconfigure) the Node.register admission
        door — one server-wide bucket, not per-namespace: a reconnect
        storm is a cluster-level event. rate <= 0 disables."""
        self.node_limiter.configure(rate, burst)

    @staticmethod
    def _args_namespace(args) -> str:
        if not isinstance(args, dict):
            return "default"
        ns = args.get("namespace")
        if not ns:
            job = args.get("job")
            ns = getattr(job, "namespace", None)
        return ns or "default"

    def _rpc_precheck(self, method: str, args) -> None:
        """Runs before EVERY dispatch (in-process and fabric-arriving):
        a federated request landing in its target region carries the
        caller's token — the sending region's HTTP-layer check used ITS
        acl state, so re-authorize against OURS (the reference resolves
        the forwarded token in the target region; non-replicated tokens
        are region-local, like non-global tokens there). The per-
        namespace rate limit also charges here: one choke point covers
        the fabric socket, in-process rpc_self, and HTTP-originated
        writes alike."""
        if self.node_limiter.enabled and method == "Node.register":
            from .. import metrics
            from ..ratelimit import RateLimitError

            wait = self.node_limiter.check("node")
            if wait > 0:
                metrics.incr("nomad.rpc.node_throttled")
                raise RateLimitError(
                    "node registration rate limit exceeded "
                    "(reconnect-storm admission door)",
                    retry_after_s=wait,
                )
        if (
            self.rpc_limiter.enabled
            and method in self._RATE_LIMITED_METHODS
        ):
            from .. import metrics
            from ..ratelimit import RateLimitError

            ns = self._args_namespace(args)
            wait = self.rpc_limiter.check(ns)
            if wait > 0:
                metrics.incr("nomad.rpc.throttled")
                raise RateLimitError(
                    f"rpc {method} rate limit exceeded for namespace "
                    f"{ns!r}",
                    retry_after_s=wait,
                )
        if (
            isinstance(args, dict)
            and args.get("__cross_region_token__") is not None
            and args.get("region") == self.region
        ):
            self._check_cross_region(method, args)

    # RPC method → (kind, capability) for federated re-authorization.
    # kind "ns": namespace capability against args' namespace;
    # kind "read": any valid token; everything unlisted needs management.
    _FEDERATED_CAPS = {
        "Job.register": ("ns", "submit-job"),
        "Job.deregister": ("ns", "submit-job"),
        "Job.revert": ("ns", "submit-job"),
        "Job.dispatch": ("ns", "dispatch-job"),
        "Job.plan": ("ns", "submit-job"),
        "Job.scale": ("ns_any", ("scale-job", "submit-job")),
        "Job.scale_status": ("ns", "read-job"),
        "Job.periodic_force": ("ns", "submit-job"),
        "Job.get": ("ns", "read-job"),
        "Job.list": ("read", None),
        "Job.allocs": ("ns", "read-job"),
        "Job.evals": ("ns", "read-job"),
        "Job.summary": ("ns", "read-job"),
        "Job.versions": ("ns", "read-job"),
        "Node.list": ("read", None),
        "Node.get": ("read", None),
        "Alloc.get": ("read", None),
        "Alloc.list": ("read", None),
        "Alloc.list_by_node": ("read", None),
        "Alloc.stop": ("alloc_ns", "alloc-lifecycle"),
        "Eval.get": ("read", None),
        "Eval.list": ("read", None),
        "Eval.allocs": ("read", None),
        "Deployment.get": ("read", None),
        "Deployment.list": ("read", None),
        "Service.list": ("read", None),
        "Service.get": ("read", None),
        "Volume.list": ("ns", "read-job"),
        "Volume.get": ("ns", "read-job"),
        "Volume.register": ("ns", "submit-job"),
        "Status.regions": ("read", None),
        "Status.leader": ("read", None),
        "Status.peers": ("read", None),
    }

    def _check_cross_region(self, method: str, args: dict) -> None:
        if not self.acl_enforce:
            return
        token = args.get("__cross_region_token__") or ""
        try:
            acl = self.server.resolve_token(token)
        except PermissionError as e:
            raise PermissionError(f"region {self.region!r}: {e}") from None
        if acl is None:
            raise PermissionError(
                f"region {self.region!r}: missing ACL token"
            )
        if acl.is_management():
            return
        rule = self._FEDERATED_CAPS.get(method)
        if rule is None:
            raise PermissionError(
                f"region {self.region!r}: {method} requires a management "
                f"token across regions"
            )
        kind, cap = rule
        if kind == "read":
            return  # any valid local token may read
        if kind == "ns_any":
            ns = args.get("namespace") or "default"
            if not any(
                acl.allow_namespace_op(ns, c) for c in cap
            ):
                raise PermissionError(
                    f"region {self.region!r}: missing any of {cap} on "
                    f"namespace {ns!r}"
                )
            return
        if kind == "alloc_ns":
            # resolve the TARGET object's namespace here — the sending
            # region's HTTP guard never saw this alloc
            try:
                alloc = self.find_alloc(args.get("alloc_id", ""))
            except LookupError:
                return  # the op itself will 404
            if not acl.allow_namespace_op(alloc.namespace, cap):
                raise PermissionError(
                    f"region {self.region!r}: missing {cap!r} on "
                    f"namespace {alloc.namespace!r}"
                )
            return
        ns = args.get("namespace") or getattr(
            args.get("job"), "namespace", None
        ) or getattr(args.get("volume"), "namespace", None) or "default"
        if not acl.allow_namespace_op(ns, cap):
            raise PermissionError(
                f"region {self.region!r}: missing {cap!r} on "
                f"namespace {ns!r}"
            )

    def region_server(self, region: str):
        """A live server's fabric addr in the named region, from gossip
        (reference nomad/server.go forwardRegion picks a random member)."""
        import random

        candidates = [
            tuple(m.addr)
            for m in self.serf.members()
            if m.tags.get("role") == "server"
            and m.status == "alive"
            and (m.tags.get("region") or "global") == region
        ]
        return random.choice(candidates) if candidates else None

    def is_leader(self) -> bool:
        return self.raft.is_leader()

    def start(self) -> None:
        self.rpc.start()
        self.raft.start()
        self.serf.start()
        self.solver_pool.start()
        self.blackbox.start()

    def join(self, seeds: list[tuple[str, int]]) -> int:
        """Gossip-join an existing cluster (reference `nomad server join` /
        server_join config). Raft adoption follows via member events."""
        return self.serf.join(seeds)

    def _on_member_event(self, kind: str, member) -> None:
        if member.tags.get("role") != "server":
            return
        # Federation: one gossip ring can span regions (the reference's
        # WAN serf), but raft is PER-REGION — a server in another region
        # must never become a raft peer (nomad/serf.go keeps LAN serf
        # per region; regions meet only at RPC forwarding).
        if (member.tags.get("region") or "global") != self.region:
            return
        # Pool health rides the same gossip events: a confirmed-dead
        # solver member fails its in-flight dispatches immediately
        # (solver_pool.py) instead of waiting out the RPC timeout.
        self.solver_pool.on_member_event(kind, member)
        # Initial bootstrap: once bootstrap_expect servers see each other,
        # every one of them derives the SAME peer map from gossip and raft
        # elections begin (reference serf.go maybeBootstrap). Cheap — runs
        # inline on the probe thread.
        if not self._bootstrapped and kind == "member-join":
            servers = {
                m.id: tuple(m.addr)
                for m in self.serf.members()
                if m.tags.get("role") == "server"
                and m.status == "alive"
                and (m.tags.get("region") or "global") == self.region
            }
            servers[self.node_id] = self.rpc.addr
            if len(servers) >= self._bootstrap_expect:
                with self.raft._lock:
                    if not self.raft.peers:
                        self.raft.peers = {
                            p: a for p, a in servers.items() if p != self.node_id
                        }
                self._bootstrapped = True
                logger.info(
                    "%s: bootstrapped raft with %d servers",
                    self.node_id,
                    len(servers),
                )
            return
        self._reconcile_q.put((kind, member))

    def _reconcile_loop(self) -> None:
        """Leader-side raft config reconciliation off the gossip thread
        (reference leader.go reconcileMember)."""
        while True:
            item = self._reconcile_q.get()
            if item is None:
                return
            kind, member = item
            if not self.raft.is_leader():
                continue
            try:
                if kind in ("member-join", "member-alive"):
                    self.raft.add_peer(member.id, tuple(member.addr))
                elif kind in ("member-failed", "member-leave"):
                    if kind == "member-failed" and not self.autopilot_config().get(
                        "CleanupDeadServers", True
                    ):
                        continue  # operator opted out of auto-removal
                    self.raft.remove_peer(member.id)
            except (NotLeaderError, TimeoutError):
                pass
            except Exception:
                logger.exception("member reconciliation failed")

    def shutdown(self) -> None:
        was_leader = self.raft.is_leader()
        self._close_reverse_sessions()
        self.blackbox.stop()
        self.solver_pool.stop()
        self.serf.stop()
        self._reconcile_q.put(None)
        self.raft.stop()
        if was_leader:
            self.server.revoke_leadership()
        self.server.shutdown()
        metrics.unregister_provider(
            "nomad.rpc.source", self._source_provider
        )
        self.rpc.shutdown()
        self.pool.shutdown()
        if self.raft_store is not None:
            self.raft_store.close()


class ClusterRPC:
    """Client-side server connection over the fabric, with failover.

    Reference: client/servers manager — the client holds a ring of server
    addresses and rotates on RPC failure; any server forwards to the
    leader. Satisfies the same five-verb interface as the in-process
    ServerRPC shim (client/client.py).
    """

    def __init__(
        self,
        addrs: list[tuple[str, int]],
        pool: Optional[ConnPool] = None,
        rpc_secret="",  # str | rpc.keyring.Keyring (shared by the agent)
        tls_context=None,  # client-side ssl ctx (rpc.tls.fabric_contexts)
    ):
        self.addrs = [tuple(a) for a in addrs]
        if pool is not None and tls_context is not None:
            # silently dropping the context would dial a TLS fabric in
            # plaintext with no hint why registration fails
            raise ValueError("pass tls_context on the pool, not both")
        self.pool = pool or ConnPool(
            secret=rpc_secret, tls_context=tls_context
        )
        # The client's heartbeat and watch threads share this object;
        # rotation must be atomic or concurrent failures double-rotate
        # past live servers.
        self._lock = threading.Lock()

    def reverse_addrs(self) -> list:
        """Server fabric addrs the ReverseDialer parks sessions on."""
        with self._lock:
            return list(self.addrs)

    def _call(self, method: str, args, timeout_s: float = 30.0):
        last: Optional[Exception] = None
        with self._lock:
            candidates = list(self.addrs)
        for addr in candidates:
            try:
                return self.pool.call(addr, method, args, timeout_s=timeout_s)
            except (ConnectionError, OSError, TimeoutError, RPCError) as e:
                last = e
                # rotate the shared ring only if this addr is still at the
                # front (another thread may have rotated already)
                with self._lock:
                    if self.addrs and self.addrs[0] == addr:
                        self.addrs.append(self.addrs.pop(0))
        raise last  # type: ignore[misc]

    def register(self, node: Node) -> float:
        return self._call("Node.register", {"node": node})

    def heartbeat(self, node_id: str) -> float:
        return self._call("Node.heartbeat", {"node_id": node_id})

    def get_client_allocs(self, node_id: str, min_index: int, timeout_s: float):
        resp = self._call(
            "Node.get_client_allocs",
            {"node_id": node_id, "min_index": min_index, "timeout_s": timeout_s},
            timeout_s=timeout_s + 10.0,
        )
        return resp["allocs"], resp["index"]

    def update_allocs(self, allocs: list[Allocation]) -> None:
        self._call("Node.update_allocs", {"allocs": allocs})

    def alloc_client_addr(self, alloc_id: str):
        out = self._call("Alloc.client_addr", {"alloc_id": alloc_id})
        return tuple(out) if out else (None, None)

    def volumes_for_alloc(self, alloc_id: str) -> list:
        return self._call("Volume.for_alloc", {"alloc_id": alloc_id})

    def services_register(self, regs: list) -> None:
        self._call("Service.register", {"regs": regs})

    def services_deregister_alloc(self, alloc_id: str) -> None:
        self._call("Service.deregister_alloc", {"alloc_id": alloc_id})

    def service_lookup(self, namespace: str, name: str) -> list:
        return self._call(
            "Service.get", {"namespace": namespace, "name": name}
        )

    def secret_read(self, namespace: str, path: str, token: str = ""):
        return self._call(
            "Secrets.read",
            {"namespace": namespace, "path": path, "token": token},
        )

    def derive_token(self, alloc_id: str, task_name: str) -> dict:
        return self._call(
            "Secrets.derive_token",
            {"alloc_id": alloc_id, "task_name": task_name},
        )

    def renew_token(self, accessor_id: str) -> float:
        return self._call(
            "Secrets.renew_token", {"accessor_id": accessor_id}
        )

    def revoke_token(self, accessor_id: str) -> None:
        self._call("Secrets.revoke_token", {"accessor_id": accessor_id})
