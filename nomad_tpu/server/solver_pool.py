"""Leader side of the solver-pool tier (docs/solver-pool.md).

The pool decouples placement capacity from raft: followers (or
dedicated ``solver``-role agents) keep warm meshes and
ResidentClusterState replicas (scheduler/tpu/remote_solve.py), and the
leader's TPUBatchWorker streams its mega-batch drains out over the RPC
fabric (``SolverPool.Solve``) instead of solving locally. The leader
keeps plan-apply/raft authority — a remote solve returns plan columns
that flow through the SAME plan verification, commit, and eval-update
path a local solve would, so a slightly stale replica costs a trimmed
plan (and a retry eval), never a wrong commit.

Dispatch policy (worker.py _solve_batch):
  * mega-batch drains route to the least-loaded healthy pool member;
  * the interactive lane (host microsolve) always solves locally — a
    network hop would eat the latency the lane exists to save;
  * an empty pool, or a member dying mid-solve, falls back to the
    local worker riding the existing DeviceFault/retry discipline
    (a member fault IS a retriable device fault to the commit stage).

Membership hangs off cluster gossip: a member advertises with the serf
tag ``solver=1`` (role = "solver" in the ``solver_pool`` agent stanza)
and health follows serf status + a short local fault cooldown after a
failed dispatch. Leadership transfer aborts in-flight dispatches so
their evals NACK (redeliver on the new leader) instead of dropping.

This module is server-side: jax must only load lazily (the scheduler/
tpu imports live inside methods), per the nomad-vet layering map.
"""

from __future__ import annotations

import copy
import logging
import threading
import time
from concurrent.futures import CancelledError
from typing import Optional

from .. import blackbox, faultplane, metrics, trace

logger = logging.getLogger("nomad_tpu.solver_pool")

# A member that just failed a dispatch sits out this long before pick()
# considers it again — serf suspicion usually confirms within the window.
FAULT_COOLDOWN_S = 5.0


class _Dispatch:
    """One in-flight remote solve: the RPC runs on its own daemon thread
    so the worker's solve stage returns immediately (phase A stays
    async, exactly like the local device dispatch)."""

    __slots__ = ("member_id", "addr", "done", "result", "error", "aborted",
                 "t0")

    def __init__(self, member_id: str, addr: tuple) -> None:
        self.member_id = member_id
        self.addr = addr
        self.done = threading.Event()
        self.result: Optional[dict] = None
        self.error: Optional[BaseException] = None
        self.aborted = False
        self.t0 = time.perf_counter()

    def fail(self, exc: BaseException) -> None:
        if not self.done.is_set():
            self.error = exc
            self.done.set()

    def abort(self) -> None:
        self.aborted = True
        self.done.set()


class RemotePendingBatch:
    """PendingEvalBatch stand-in for a pool-dispatched solve. The commit
    stage consumes it unchanged: finish() blocks on the RPC instead of
    the device; a member fault raises a retriable DeviceFault so the
    existing device-failover path re-solves on the host oracle; the
    chain surface is inert (a remote solve never consumes or produces a
    local used' tensor — the applier's verification is the serializer
    between overlapping remote batches)."""

    chain = None
    chain_accepted = False
    used_micro = False

    def __init__(self, pool: "SolverPool", dispatch: _Dispatch, snapshot,
                 evals: list, planner, config) -> None:
        self._pool = pool
        self._dispatch = dispatch
        self._snapshot = snapshot
        self._evals = evals
        self._planner = planner
        self._config = config
        self._finished = False
        self._plans = None

    def finish(self):
        if self._finished:
            return self._plans
        d = self._dispatch
        d.done.wait(self._pool.solve_timeout_s + 5.0)
        if d.aborted:
            # leadership transfer (or shutdown) mid-solve: the commit
            # stage's outer guard nacks the batch so its evals redeliver
            # on the new leader — aborting must never DROP them
            raise CancelledError("solver pool dispatch aborted")
        if d.error is not None or d.result is None:
            err = d.error or TimeoutError("solver pool solve timed out")
            raise faultplane.DeviceFault(
                f"pool member {d.member_id} failed mid-solve: "
                f"{type(err).__name__}: {err}",
                retriable=True,
            )
        out = d.result
        # Followup evals minted by the member's reconcile pass
        # (CollectingPlanner): applied HERE, on the leader's raft — if
        # leadership was just lost this raises NotLeaderError and the
        # commit stage nacks, same as a local solve's create_eval.
        for fe in out.get("followups") or []:
            self._planner.create_eval(fe)
        dt = time.perf_counter() - d.t0
        metrics.observe("nomad.solver.pool.remote_seconds", dt)
        self._pool.note_completed(d)
        self._plans = out["plans"]
        self._finished = True
        return self._plans

    def solve_host_fallback(self):
        """Member died mid-solve: re-solve the same evals locally on the
        host oracle path (no device, no pool). The failed member's
        followups were never applied, so this is a clean re-solve."""
        from ..scheduler.tpu import solve_eval_batch

        cfg = copy.copy(self._config)
        cfg.small_batch_threshold = 1 << 62
        return solve_eval_batch(
            self._snapshot, self._planner, self._evals, cfg
        )


class SolverPoolEndpoint:
    """RPC surface every server exposes (verbs ``SolverPool.Solve`` /
    ``Sync`` / ``Status``). The warm RemoteSolver engine is built
    lazily on the first Solve/Sync — a server that never advertises and
    never gets dispatched to never loads jax for it."""

    def __init__(self, cluster, pool: "SolverPool") -> None:
        self.cs = cluster
        self.pool = pool
        self._lock = threading.Lock()
        self._solver = None

    def local_solver(self, build: bool = True):
        with self._lock:
            if self._solver is None and build:
                from ..scheduler.context import SchedulerConfig
                from ..scheduler.tpu.remote_solve import RemoteSolver

                # the inner Server owns the state store (the ClusterServer
                # is the raft/gossip shell around it)
                self._solver = RemoteSolver(
                    getattr(self.cs, "server", self.cs),
                    config=SchedulerConfig(backend="tpu"),
                    node_id=self.cs.node_id,
                )
            return self._solver

    def solve(self, args):
        args = args or {}
        solver = self.local_solver()
        with trace.span(
            trace.current(), "solver.pool.remote",
            member=self.cs.node_id, evals=len(args.get("evals") or []),
        ):
            return solver.solve(
                args.get("evals") or [],
                int(args.get("min_index") or 0),
                extra_usage=args.get("extra_usage") or None,
                timeout_s=float(args.get("timeout_s") or 5.0),
            )

    def sync(self, args):
        args = args or {}
        solver = self.local_solver()
        return {
            "last_sync": solver.warm(int(args.get("min_index") or 0)),
            "member": self.cs.node_id,
        }

    def status(self, args):
        solver = self.local_solver(build=False)
        if solver is None:
            return {"node_id": self.cs.node_id, "resident": False,
                    "warmups": 0, "solves": 0, "syncs": 0, "in_flight": 0,
                    "last_sync": "cold"}
        return solver.stats()

    # the wire verbs are capitalized (``SolverPool.Solve`` — the
    # reference's Go-style RPC names); keep pythonic methods callable too
    Solve = solve
    Sync = sync
    Status = status


class SolverPool:
    """Pool tracker + dispatcher, one per ClusterServer.

    Always constructed (cheap); a cluster with no advertised members
    just always falls back local. ``role == "solver"`` additionally
    advertises THIS server as a member (serf tag ``solver=1``) and runs
    the periodic warm loop that keeps its resident replica's delta-sync
    path hot across leadership churn."""

    def __init__(self, cluster, role: str = "", members=(),
                 sync_interval_s: float = 2.0) -> None:
        self.cluster = cluster
        self.role = role or ""
        self.static_members = tuple(members or ())
        self.sync_interval_s = float(sync_interval_s)
        self.solve_timeout_s = 30.0
        self.endpoint = SolverPoolEndpoint(cluster, self)
        self._lock = threading.Lock()
        self._inflight: set[_Dispatch] = set()
        # member id -> leader-side per-member counters
        self._member_stats: dict[str, dict] = {}
        self._fault_until: dict[str, float] = {}
        self.dispatched = 0
        self.completed = 0
        self.faults = 0
        self.aborted = 0
        self.fallback_local = 0
        self._warm_stop: Optional[threading.Event] = None
        self._warm_thread: Optional[threading.Thread] = None
        self._provider = metrics.register_provider(
            "nomad.solver.pool", self._gauges
        )
        if self.role == "solver":
            self._advertise(True)

    # -- config / lifecycle --------------------------------------------

    def _advertise(self, on: bool) -> None:
        serf = self.cluster.serf
        tags = serf.local.tags
        if on:
            if tags.get("solver") == "1":
                return
            tags["solver"] = "1"
        else:
            if "solver" not in tags:
                return
            tags.pop("solver", None)
        # a tag change rides gossip on a higher incarnation (membership
        # merge adopts tags from the fresher record)
        serf.local.incarnation += 1

    def configure(self, role: str, members=(),
                  sync_interval_s: Optional[float] = None) -> bool:
        """SIGHUP-reloadable knobs (Agent.reload). Returns True when
        anything changed."""
        changed = False
        with self._lock:
            role = role or ""
            if role != self.role:
                self.role = role
                self._advertise(role == "solver")
                changed = True
            members = tuple(members or ())
            if members != self.static_members:
                self.static_members = members
                changed = True
            if (
                sync_interval_s is not None
                and float(sync_interval_s) != self.sync_interval_s
            ):
                self.sync_interval_s = float(sync_interval_s)
                changed = True
        if changed:
            self._reconcile_warm_loop()
        return changed

    def start(self) -> None:
        self._reconcile_warm_loop()

    def _reconcile_warm_loop(self) -> None:
        if self.role == "solver" and self._warm_thread is None:
            self._warm_stop = threading.Event()
            self._warm_thread = threading.Thread(
                target=self._warm_loop, args=(self._warm_stop,),
                name=f"solver-pool-warm-{self.cluster.node_id}",
                daemon=True,
            )
            self._warm_thread.start()
        elif self.role != "solver" and self._warm_thread is not None:
            self._warm_stop.set()
            self._warm_thread = None

    def _warm_loop(self, stop: threading.Event) -> None:
        """The member-side sync loop: a periodic delta sync against the
        local raft replica keeps the resident tensors' fingerprint
        current, so the first batch a NEW leader dispatches here hits
        the scatter path — zero warmup on failover."""
        while not stop.wait(self.sync_interval_s):
            try:
                self.endpoint.local_solver().warm()
            except Exception:
                # replica catching up / store mid-restore: next tick
                logger.debug("solver pool warm tick failed", exc_info=True)

    def stop(self) -> None:
        if self._warm_stop is not None:
            self._warm_stop.set()
            self._warm_thread = None
        self.abort_inflight()
        metrics.unregister_provider("nomad.solver.pool", self._provider)

    # -- membership -----------------------------------------------------

    def members(self) -> list[dict]:
        """Current pool membership from gossip: servers advertising
        ``solver=1`` (optionally filtered by the static ``members``
        allowlist), with serf status and leader-side dispatch stats."""
        now = time.monotonic()
        out = []
        for m in self.cluster.serf.members():
            if m.tags.get("solver") != "1":
                continue
            if m.tags.get("role") != "server":
                continue
            if self.static_members and m.id not in self.static_members:
                continue
            st = self._member_stats.get(m.id, {})
            out.append({
                "id": m.id,
                "addr": list(m.addr),
                "status": m.status,
                "self": m.id == self.cluster.node_id,
                "cooling": self._fault_until.get(m.id, 0.0) > now,
                "in_flight": st.get("in_flight", 0),
                "dispatched": st.get("dispatched", 0),
                "faults": st.get("faults", 0),
            })
        return out

    def _pick(self) -> Optional[tuple[str, tuple]]:
        """Least-loaded healthy member, excluding this server (the
        leader solving for itself over a socket would just be the local
        path with extra hops)."""
        best = None
        for m in self.members():
            if m["self"] or m["status"] != "alive" or m["cooling"]:
                continue
            if best is None or m["in_flight"] < best["in_flight"]:
                best = m
        if best is None:
            return None
        return best["id"], tuple(best["addr"])

    def on_member_event(self, kind: str, member) -> None:
        """Fed from ClusterServer._on_member_event: a pool member
        confirmed dead by gossip fails its in-flight dispatches NOW
        instead of waiting out the RPC timeout."""
        if member.tags.get("solver") != "1":
            return
        if kind in ("member-failed", "member-leave"):
            with self._lock:
                pending = [
                    d for d in self._inflight if d.member_id == member.id
                ]
            for d in pending:
                d.fail(ConnectionError(f"pool member {member.id} {kind}"))

    # -- dispatch -------------------------------------------------------

    def dispatch_batch(self, evals: list, snapshot, planner,
                       config, extra_usage: Optional[dict] = None,
                       ) -> Optional[RemotePendingBatch]:
        """Route one mega-batch to the pool. Returns None (caller keeps
        the local path) when no healthy member is available."""
        picked = self._pick()
        if picked is None:
            self.fallback_local += 1
            metrics.incr("nomad.solver.pool.fallback_local")
            return None
        member_id, addr = picked
        d = _Dispatch(member_id, addr)
        with self._lock:
            self._inflight.add(d)
            st = self._member_stats.setdefault(
                member_id, {"in_flight": 0, "dispatched": 0, "faults": 0}
            )
            st["in_flight"] += 1
            st["dispatched"] += 1
            self.dispatched += 1
        metrics.incr("nomad.solver.pool.dispatched")
        args = {
            "evals": evals,
            "min_index": snapshot.index,
            "extra_usage": extra_usage,
        }

        def _call() -> None:
            try:
                res = self.cluster.pool.call(
                    addr, "SolverPool.Solve", args,
                    timeout_s=self.solve_timeout_s,
                )
                if not d.done.is_set():
                    d.result = res
                    d.done.set()
            except Exception as e:
                self._record_fault(d, e)
            finally:
                with self._lock:
                    st["in_flight"] = max(0, st["in_flight"] - 1)

        threading.Thread(
            target=_call, name=f"solver-pool-dispatch-{member_id}",
            daemon=True,
        ).start()
        return RemotePendingBatch(self, d, snapshot, evals, planner, config)

    def _record_fault(self, d: _Dispatch, exc: BaseException) -> None:
        with self._lock:
            self.faults += 1
            st = self._member_stats.get(d.member_id)
            if st is not None:
                st["faults"] += 1
            self._fault_until[d.member_id] = (
                time.monotonic() + FAULT_COOLDOWN_S
            )
        metrics.incr("nomad.solver.pool.member_fault")
        blackbox.record(
            blackbox.KIND_POOL_FAULT, d.member_id,
            error=f"{type(exc).__name__}: {exc}",
        )
        logger.warning(
            "solver pool member %s failed: %s: %s",
            d.member_id, type(exc).__name__, exc,
        )
        d.fail(exc)

    def note_completed(self, d: _Dispatch) -> None:
        with self._lock:
            self.completed += 1
            self._inflight.discard(d)

    def abort_inflight(self) -> int:
        """Leadership transfer / shutdown: every in-flight dispatch
        resolves ABORTED so the commit stage nacks its batch (the evals
        redeliver on the new leader's broker). Never drops."""
        with self._lock:
            pending = [d for d in self._inflight if not d.done.is_set()]
            self._inflight.clear()
        for d in pending:
            d.abort()
            self.aborted += 1
            metrics.incr("nomad.solver.pool.aborted")
        return len(pending)

    # -- observability --------------------------------------------------

    def _gauges(self) -> dict:
        members = self.members()
        healthy = sum(
            1 for m in members
            if m["status"] == "alive" and not m["self"] and not m["cooling"]
        )
        return {
            "members": healthy,
            "in_flight": sum(m["in_flight"] for m in members),
        }

    def stats_snapshot(self) -> dict:
        """Live pool state for /v1/solver/pool and the operator-top
        solver panel (same idiom as the broker/plan-queue
        stats_snapshot surfaces)."""
        local = self.endpoint.local_solver(build=False)
        with self._lock:
            inflight = len(self._inflight)
        return {
            "role": self.role,
            "sync_interval_s": self.sync_interval_s,
            "static_members": list(self.static_members),
            "dispatched": self.dispatched,
            "completed": self.completed,
            "faults": self.faults,
            "aborted": self.aborted,
            "fallback_local": self.fallback_local,
            "in_flight": inflight,
            "members": self.members(),
            "local": local.stats() if local is not None else None,
        }

    def pool_status(self, per_member_timeout_s: float = 2.0) -> dict:
        """stats_snapshot plus each member's own ``SolverPool.Status``,
        pulled in parallel with a bounded per-member deadline (the
        cluster_health aggregation pattern: a partitioned member slots
        an error row, never a hang)."""
        out = self.stats_snapshot()
        rows: dict[str, dict] = {}

        def _pull(mid: str, addr: tuple) -> None:
            try:
                if mid == self.cluster.node_id:
                    rows[mid] = self.endpoint.status(None)
                else:
                    rows[mid] = self.cluster.pool.call(
                        addr, "SolverPool.Status", {},
                        timeout_s=per_member_timeout_s,
                    )
            except Exception as e:
                rows[mid] = {"node_id": mid, "error": str(e)}

        threads = []
        for m in out["members"]:
            t = threading.Thread(
                target=_pull, args=(m["id"], tuple(m["addr"])),
                name=f"solver-pool-status-{m['id']}", daemon=True,
            )
            t.start()
            threads.append(t)
        for t in threads:
            t.join(per_member_timeout_s + 0.5)
        for m in out["members"]:
            m["remote"] = rows.get(m["id"])
        return out
