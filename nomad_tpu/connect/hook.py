"""Server-side Connect admission hook.

Reference: nomad/job_endpoint_hooks.go:60 (jobImpliedConstraints +
jobConnectHook) — groups whose services carry a connect stanza get a
sidecar task, its port, and the mesh registration injected at job
admission, so the scheduler and clients see a perfectly ordinary job.
"""

from __future__ import annotations

import json

from ..structs.structs import (
    Port,
    Resources,
    Service,
    Task,
    Template,
)

#: in-namespace port the Nth connect service's sidecar listens on
SIDECAR_BASE_PORT = 20000


def connect_sidecar_port_label(service_name: str) -> str:
    return f"connect-proxy-{service_name}"


def mesh_service_name(service_name: str) -> str:
    return f"{service_name}-sidecar-proxy"


class ConnectValidationError(ValueError):
    pass


def inject_connect_sidecars(job) -> None:
    """Mutate the job in place: one sidecar task per connect service.
    Idempotent — re-registering an already-injected job changes
    nothing."""
    for tg in job.task_groups:
        connect_services = [
            s
            for s in tg.services
            if s.connect is not None and s.connect.sidecar_service is not None
        ]
        if not connect_services:
            continue
        if not tg.networks or tg.networks[0].mode != "bridge":
            raise ConnectValidationError(
                f"group {tg.name!r}: connect services require bridge "
                "network mode"
            )
        net = tg.networks[0]
        port_to = {
            p.label: (p.to or p.value)
            for p in list(net.reserved_ports) + list(net.dynamic_ports)
        }
        existing_tasks = {t.name for t in tg.tasks}
        existing_services = {s.name for s in tg.services}
        for idx, svc in enumerate(connect_services):
            local_port = port_to.get(svc.port_label)
            if not local_port:
                if svc.port_label in port_to:
                    # the label exists but has neither `to` nor a static
                    # value — the sidecar must know the in-namespace port
                    # at admission time
                    raise ConnectValidationError(
                        f"connect service {svc.name!r}: port "
                        f"{svc.port_label!r} needs a `to = <port>` "
                        "mapping (or a static port) for connect"
                    )
                raise ConnectValidationError(
                    f"connect service {svc.name!r}: port "
                    f"{svc.port_label!r} is not defined on the group "
                    "network"
                )
            label = connect_sidecar_port_label(svc.name)
            listen_port = SIDECAR_BASE_PORT + idx
            if label not in port_to:
                net.dynamic_ports.append(Port(label=label, to=listen_port))
                port_to[label] = listen_port
            if mesh_service_name(svc.name) not in existing_services:
                tg.services.append(
                    Service(
                        name=mesh_service_name(svc.name),
                        port_label=label,
                        tags=["sidecar-proxy"],
                    )
                )
            task_name = f"connect-proxy-{svc.name}"
            if task_name in existing_tasks:
                continue
            tg.tasks.append(
                _sidecar_task(task_name, listen_port, local_port, svc)
            )


def _sidecar_task(task_name, listen_port, local_port, svc) -> Task:
    upstreams = svc.connect.sidecar_service.upstreams
    config = {
        "inbound": {"listen_port": listen_port, "local_port": local_port},
        "upstreams": [
            {
                "name": u.destination_name,
                "listen_port": u.local_bind_port,
                "addresses_file": f"local/upstream-{u.destination_name}.addrs",
            }
            for u in upstreams
        ],
    }
    templates = [
        Template(
            dest_path="local/sidecar.json",
            embedded_tmpl=json.dumps(config),
            change_mode="noop",
        )
    ]
    for u in upstreams:
        templates.append(
            Template(
                dest_path=f"local/upstream-{u.destination_name}.addrs",
                embedded_tmpl=(
                    '{{service "'
                    + mesh_service_name(u.destination_name)
                    + '"}}'
                ),
                change_mode="noop",  # the sidecar watches the file
            )
        )
    return Task(
        name=task_name,
        driver="rawexec",
        # the CLIENT resolves its own interpreter/package location via
        # its nomad fingerprint attributes (task config and env are
        # interpolated node-side) — the server's paths never leak into
        # the task
        config={
            "command": "${attr.unique.nomad.python}",
            "args": ["-m", "nomad_tpu.connect.sidecar", "local/sidecar.json"],
        },
        env={"PYTHONPATH": "${attr.unique.nomad.pkg_root}"},
        resources=Resources(cpu=50, memory_mb=64),
        templates=templates,
    )
