"""Service mesh (Connect analog).

Reference: the Consul Connect integration — sidecar task injection
(nomad/job_endpoint_hooks.go:60), the envoy bootstrap hook
(client/allocrunner/taskrunner/envoybootstrap_hook.go), and sidecar
service registration (command/agent/consul/connect.go).

TPU-native redesign: there is no Consul and no Envoy here. The mesh is
built from this framework's own parts —

  * the server's job admission hook (hook.py) injects a sidecar TASK
    (``python -m nomad_tpu.connect.sidecar``) plus its dynamic port and
    a ``<service>-sidecar-proxy`` catalog registration into any group
    whose service carries a ``connect { sidecar_service {} }`` stanza;
  * the sidecar's config is a TEMPLATE rendered by the client's
    template engine — upstream addresses come from the native service
    catalog via ``{{service "<dest>-sidecar-proxy"}}`` and re-render on
    change (change_mode=noop; the sidecar watches the file);
  * the sidecar itself (sidecar.py) is a TCP relay: an inbound listener
    forwarding mesh traffic to the local service port, and one local
    listener per upstream forwarding to the destination's advertised
    sidecar, exactly the data path envoy provides in the reference.

mTLS between sidecars is NOT implemented (the reference derives leaf
certs from the Consul CA); transport security today is the cluster
network — documented as a known departure.
"""

from .hook import connect_sidecar_port_label, inject_connect_sidecars

__all__ = ["inject_connect_sidecars", "connect_sidecar_port_label"]
