"""The sidecar proxy process (the envoy-analog data plane).

Run as: python -m nomad_tpu.connect.sidecar <config.json>

Config (rendered by the client's template engine, re-rendered when the
service catalog changes; this process re-reads it on mtime change):

    {
      "inbound": {"listen_port": 20000, "local_port": 8080},
      "upstreams": [
        {"name": "api", "listen_port": 5000,
         "addresses_file": "local/upstream-api.addrs"}
      ]
    }

Each addresses_file holds "host:port" lines — the destination's
advertised sidecars, rendered from the service catalog by the client's
template engine and re-rendered when the catalog changes; this process
re-reads on mtime change. Inbound mesh traffic arriving on listen_port
relays to the co-located service at 127.0.0.1:local_port; each upstream
gets a local listener relaying to one of the destination's sidecars
(round-robin)."""

from __future__ import annotations

import itertools
import json
import os
import sys
import time


class _Relay:
    """One listener relaying to a dynamic target list (round-robin),
    built on the shared TcpRelay data plane."""

    def __init__(self, listen_port: int, targets: list[str]) -> None:
        from nomad_tpu.tcprelay import TcpRelay

        self._targets = targets
        self._rr = itertools.count()
        self._relay = TcpRelay(listen_port, self._pick)

    def set_targets(self, targets: list[str]) -> None:
        self._targets = targets

    def _pick(self) -> tuple[str, int] | None:
        targets = self._targets
        if not targets:
            return None
        raw = targets[next(self._rr) % len(targets)]
        host, _, port = raw.rpartition(":")
        try:
            return (host, int(port))
        except ValueError:
            return None


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _read_addresses(path: str) -> list[str]:
    try:
        with open(path) as f:
            return [ln.strip() for ln in f if ln.strip()]
    except OSError:
        return []


def main() -> int:
    if len(sys.argv) != 2:
        sys.stderr.write("usage: sidecar <config.json>\n")
        return 2
    cfg = _load(sys.argv[1])
    relays: dict[str, _Relay] = {}
    inbound = cfg.get("inbound")
    if inbound:
        relays["__inbound__"] = _Relay(
            int(inbound["listen_port"]),
            [f"127.0.0.1:{inbound['local_port']}"],
        )
    watched: list[tuple[str, str, float]] = []  # (name, path, mtime)
    for up in cfg.get("upstreams", []):
        addr_path = up.get("addresses_file", "")
        relays[up["name"]] = _Relay(
            int(up["listen_port"]), _read_addresses(addr_path)
        )
        try:
            mtime = os.path.getmtime(addr_path)
        except OSError:
            mtime = 0.0
        watched.append((up["name"], addr_path, mtime))
    sys.stderr.write("sidecar up\n")
    sys.stderr.flush()
    while True:
        time.sleep(1.0)
        for i, (name, addr_path, last) in enumerate(watched):
            try:
                mtime = os.path.getmtime(addr_path)
            except OSError:
                continue
            if mtime != last:
                watched[i] = (name, addr_path, mtime)
                relays[name].set_targets(_read_addresses(addr_path))


if __name__ == "__main__":
    sys.exit(main())
