"""The sidecar proxy process (the envoy-analog data plane).

Run as: python -m nomad_tpu.connect.sidecar <config.json>

Config (rendered by the client's template engine, re-rendered when the
service catalog changes; this process re-reads it on mtime change):

    {
      "inbound": {"listen_port": 20000, "local_port": 8080},
      "upstreams": [
        {"name": "api", "listen_port": 5000,
         "addresses_file": "local/upstream-api.addrs"}
      ]
    }

Each addresses_file holds "host:port" lines — the destination's
advertised sidecars, rendered from the service catalog by the client's
template engine and re-rendered when the catalog changes; this process
re-reads on mtime change. Inbound mesh traffic arriving on listen_port
relays to the co-located service at 127.0.0.1:local_port; each upstream
gets a local listener relaying to one of the destination's sidecars
(round-robin)."""

from __future__ import annotations

import itertools
import json
import os
import sys
import time


def _default_gateway() -> str | None:
    """This namespace's IPv4 default-gateway address, or None.

    ip(8) first — netlink answers for the CALLING namespace, which is
    what the nsenter'd sidecar needs; /proc/net/route is only the
    fallback because sandboxed kernels (gVisor-style) serve the host's
    table through procfs regardless of the reader's netns."""
    import socket
    import struct
    import subprocess

    try:
        proc = subprocess.run(
            ["ip", "route", "show", "default"],
            capture_output=True, text=True, timeout=5,
        )
        for line in proc.stdout.splitlines():
            parts = line.split()
            if len(parts) >= 3 and parts[0] == "default" and parts[1] == "via":
                return parts[2]
    except (OSError, subprocess.SubprocessError):
        pass
    try:
        with open("/proc/net/route") as f:
            next(f, None)  # header
            for line in f:
                parts = line.split()
                if len(parts) < 4:
                    continue
                dest, gw, flags = parts[1], parts[2], int(parts[3], 16)
                # default route (0.0.0.0) with RTF_GATEWAY set
                if dest == "00000000" and flags & 0x2:
                    return socket.inet_ntoa(
                        struct.pack("<L", int(gw, 16))
                    )
    except (OSError, ValueError):
        pass
    return None


class _Relay:
    """One listener relaying to a dynamic target list (round-robin),
    built on the shared TcpRelay data plane.

    Each pick also offers the (gateway, port) rewrite as a dial
    FALLBACK: on NAT-less hosts (no iptables/nft — client/network.py
    logs the condition) a netns'd dialer has NO ROUTE to the host's own
    advertised IP, but the same host-port listener is reachable through
    the bridge gateway address. Two guards keep the fallback from ever
    rerouting a stream that should fail: (1) TcpRelay only takes it on
    a no-route dial error (ENETUNREACH/EHOSTUNREACH) — a refused or
    timed-out primary fails the connection; (2) it is only offered when
    the target IS this host's own advertised IP (NOMAD_HOST_IP, set by
    the client's task env — the address is invisible from inside the
    netns), so a dead CROSS-host target that happens to raise
    EHOSTUNREACH (ARP/ICMP host-unreachable on the same L2) is never
    rewritten to whatever occupies the same port on the gateway. When
    NOMAD_HOST_IP is absent (pre-upgrade client), the fallback keeps
    the errno guard only — single-host dev topologies are the only
    NAT-less deployments we support, and failing them closed would
    break the hairpin path the fallback exists for."""

    def __init__(self, listen_port: int, targets: list[str]) -> None:
        from nomad_tpu.tcprelay import TcpRelay

        self._targets = targets
        self._rr = itertools.count()
        self._gateway = _default_gateway()
        self._host_ip = os.environ.get("NOMAD_HOST_IP", "")
        self._relay = TcpRelay(listen_port, self._pick)

    def set_targets(self, targets: list[str]) -> None:
        self._targets = targets

    def _pick(self) -> list[tuple[str, int]] | None:
        targets = self._targets
        if not targets:
            return None
        raw = targets[next(self._rr) % len(targets)]
        host, _, port = raw.rpartition(":")
        try:
            cands = [(host, int(port))]
        except ValueError:
            return None
        gw = self._gateway
        hairpin = (
            host == self._host_ip
            if self._host_ip
            else host not in ("127.0.0.1", "localhost")
        )
        if gw and host != gw and hairpin:
            cands.append((gw, cands[0][1]))
        return cands


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _read_addresses(path: str) -> list[str]:
    try:
        with open(path) as f:
            return [ln.strip() for ln in f if ln.strip()]
    except OSError:
        return []


def main() -> int:
    if len(sys.argv) != 2:
        sys.stderr.write("usage: sidecar <config.json>\n")
        return 2
    cfg = _load(sys.argv[1])
    relays: dict[str, _Relay] = {}
    inbound = cfg.get("inbound")
    if inbound:
        relays["__inbound__"] = _Relay(
            int(inbound["listen_port"]),
            [f"127.0.0.1:{inbound['local_port']}"],
        )
    watched: list[tuple[str, str, float]] = []  # (name, path, mtime)
    for up in cfg.get("upstreams", []):
        addr_path = up.get("addresses_file", "")
        relays[up["name"]] = _Relay(
            int(up["listen_port"]), _read_addresses(addr_path)
        )
        try:
            mtime = os.path.getmtime(addr_path)
        except OSError:
            mtime = 0.0
        watched.append((up["name"], addr_path, mtime))
    sys.stderr.write("sidecar up\n")
    sys.stderr.flush()
    while True:
        time.sleep(1.0)
        for i, (name, addr_path, last) in enumerate(watched):
            try:
                mtime = os.path.getmtime(addr_path)
            except OSError:
                continue
            if mtime != last:
                watched[i] = (name, addr_path, mtime)
                relays[name].set_targets(_read_addresses(addr_path))


if __name__ == "__main__":
    sys.exit(main())
