"""Eval-lifecycle tracing: spans from broker enqueue to raft apply.

Reference intent: the observability layer every production orchestrator
grows (the reference ships go-metrics timers per subsystem; OpenTelemetry
spans are the shape modern stacks use) — per-request spans with context
propagation, so the wall time of one evaluation can be decomposed across
broker wait → worker solve → device round-trip → plan queue → verify →
raft apply without hand-wired stage timers.

Design:

  * ``Span`` — name, start/end (monotonic ns), parent link, attrs.
  * ``TraceContext`` — one trace: a root span plus children appended from
    any thread (per-context lock). A per-context *thread-local* active-
    span stack gives automatic parenting: ``ctx.span("x")`` nested inside
    ``ctx.span("y")`` becomes its child, and pre-timed stages recorded via
    :func:`stage` attach to whatever span the recording thread has open.
  * ``TraceRecorder`` — bounded ring buffer of finished traces (the
    server's ``/v1/traces`` surface reads it; ``operator trace`` renders
    it). Drops-oldest on overflow; counters ride the metrics registry.
  * context propagation — a thread-local *current* context
    (:func:`current`/:func:`use`) carries the trace through call chains;
    the RPC fabric forwards ``{"id", "parent"}`` in the request envelope
    and returns the remote segment's spans in the response, so a trace
    stitches client-submit on a follower to raft-apply on the leader
    (rpc/client.py + rpc/server.py).

Zero-allocation no-op path: tracing is OFF by default. When disabled,
:func:`start_trace` returns ``None``, :func:`span` returns a module-level
singleton no-op context manager, and :func:`stage` is a dict lookup + two
attribute reads — nothing is allocated and nothing is locked, so the
solver/broker hot paths pay only a predictable handful of instructions.

Clocks: spans use ``time.monotonic_ns`` (never wall time — NTP steps
would corrupt durations). Remote segments carry their own monotonic base;
the RPC client re-bases merged spans onto the local call span's start, so
a stitched tree renders consistently (absolute cross-host alignment is
not claimed, only per-segment durations).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Optional

__all__ = [
    "Span",
    "TraceContext",
    "TraceRecorder",
    "configure",
    "critical_path",
    "current",
    "enabled",
    "prune_thread_spans",
    "recorder",
    "self_times",
    "set_current",
    "set_enabled",
    "span",
    "stack_self_times",
    "stage",
    "stage_attrs",
    "start_trace",
    "thread_spans",
    "use",
]

now_ns = time.monotonic_ns

# module flag, read without a lock (GIL-atomic; flips are rare operator
# actions — agent config / SIGHUP reload / tests)
_enabled = False

# thread ident -> name of that thread's INNERMOST open (stack-parented)
# span — the host profiler's span-correlation feed (nomad_tpu/hostobs.py
# attributes each wall-clock sample to thread-role x active span). Plain
# dict mutated with GIL-atomic single-key stores/pops from the owning
# thread only; the sampler reads other threads' entries racily, which
# for a statistical profiler only ever mis-attributes the one sample
# straddling a span boundary. Detached spans (opened on one thread,
# ended on another) never touch it — they are not stack-parented and do
# not represent the opener's current work.
_thread_spans: dict[int, str] = {}


def thread_spans() -> dict[int, str]:
    """Live thread-ident -> active-span-name map (see above). The dict
    object is stable for the process lifetime; callers hold the
    reference and .get() per sample."""
    return _thread_spans


def prune_thread_spans(live_idents) -> None:
    """Drop entries for dead threads (a thread that exited with a span
    still open leaks its entry; the host profiler prunes against the
    idents it actually sampled)."""
    for tid in [t for t in _thread_spans if t not in live_idents]:
        _thread_spans.pop(tid, None)


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


class Span:
    __slots__ = ("name", "span_id", "parent_id", "start_ns", "end_ns", "attrs")

    def __init__(
        self,
        name: str,
        span_id: str,
        parent_id: str = "",
        start_ns: int = 0,
        end_ns: int = 0,
        attrs: Optional[dict] = None,
    ) -> None:
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.attrs = attrs

    @property
    def duration_ns(self) -> int:
        return max(0, self.end_ns - self.start_ns)

    def to_wire(self) -> dict:
        d = {
            "name": self.name,
            "id": self.span_id,
            "parent": self.parent_id,
            "start": self.start_ns,
            "end": self.end_ns,
        }
        if self.attrs:
            d["attrs"] = self.attrs
        return d

    @staticmethod
    def from_wire(d: dict) -> "Span":
        return Span(
            d.get("name", ""),
            d.get("id", ""),
            d.get("parent", ""),
            int(d.get("start", 0)),
            int(d.get("end", 0)),
            d.get("attrs") or None,
        )


class _SpanHandle:
    """Context-manager handle for an open span (ends it on exit)."""

    __slots__ = ("_ctx", "_span")

    def __init__(self, ctx: "TraceContext", span: Span) -> None:
        self._ctx = ctx
        self._span = span

    @property
    def span(self) -> Span:
        return self._span

    def set_attr(self, key: str, value) -> None:
        if self._span.attrs is None:
            self._span.attrs = {}
        self._span.attrs[key] = value

    def __enter__(self) -> "_SpanHandle":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.set_attr("error", exc_type.__name__)
        self._ctx.end_span(self._span)


class _NoopSpan:
    """Singleton no-op: the disabled path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None

    def set_attr(self, key, value):
        return None

    span = None


NOOP_SPAN = _NoopSpan()


class TraceContext:
    """One trace: a root span plus concurrently-appended children."""

    __slots__ = (
        "trace_id",
        "name",
        "attrs",
        "spans",
        "root",
        "remote",
        "_lock",
        "_seq",
        "_prefix",
        "_active",
        "_finished",
    )

    def __init__(
        self,
        name: str,
        trace_id: str = "",
        attrs: Optional[dict] = None,
        parent_id: str = "",
        remote: bool = False,
    ) -> None:
        # pooled ids (structs.generate_uuid): a fresh urandom syscall
        # per trace measured ~0.14ms on the bench box — real overhead
        # against the 0.95x enabled-throughput gate
        from .structs import generate_uuid

        uid = generate_uuid().replace("-", "")
        self.trace_id = trace_id or uid[:16]
        self.name = name
        self.attrs = dict(attrs) if attrs else {}
        # span-id prefix unique per context so merged remote segments
        # can never collide with local counter-derived ids
        self._prefix = uid[16:24]
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self.remote = remote
        self._finished = False
        # per-THREAD active-span stack: stages recorded by the solve
        # thread parent under the solve thread's open span while the
        # commit thread's stages parent under its own — no cross-talk.
        self._active = threading.local()
        self.root = Span(
            name, f"{self._prefix}-0", parent_id, now_ns(), 0, None
        )
        self.spans: list[Span] = [self.root]

    # -- span lifecycle ------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._active, "stack", None)
        if st is None:
            st = self._active.stack = []
        return st

    def _parent_id(self) -> str:
        st = self._stack()
        return st[-1].span_id if st else self.root.span_id

    def start_span(
        self,
        name: str,
        parent: Optional[Span] = None,
        detached: bool = False,
        **attrs,
    ) -> Span:
        """detached=True skips the active-span stack: for spans opened on
        one thread and ended on another (the broker's queue-wait span),
        where stack discipline would mis-parent the opener's later
        spans."""
        pid = parent.span_id if parent is not None else self._parent_id()
        # lock-free: next() on the shared counter and list.append are
        # both GIL-atomic, and readers (to_wire) snapshot the list —
        # span creation is the enabled path's hottest op (~35us with a
        # lock on the bench box, against the 0.95x throughput gate)
        s = Span(
            name, f"{self._prefix}-{next(self._seq)}", pid,
            now_ns(), 0, attrs or None,
        )
        self.spans.append(s)
        if not detached:
            self._stack().append(s)
            # host-profiler span correlation: one GIL-atomic dict store
            _thread_spans[threading.get_ident()] = name
        return s

    def end_span(self, s: Span) -> None:
        s.end_ns = now_ns()
        st = self._stack()
        if st and st[-1] is s:
            st.pop()
        elif s in st:  # out-of-order end (defensive)
            st.remove(s)
        else:
            return  # detached span: never on the profiler registry
        tid = threading.get_ident()
        if st:
            _thread_spans[tid] = st[-1].name
        elif getattr(_tls, "ctx", None) is self:
            # back to the root: the thread still runs under this trace
            _thread_spans[tid] = self.name
        else:
            _thread_spans.pop(tid, None)

    def span(
        self, name: str, parent: Optional[Span] = None, **attrs
    ) -> _SpanHandle:
        return _SpanHandle(self, self.start_span(name, parent=parent, **attrs))

    def add_span(
        self,
        name: str,
        start_ns: int,
        end_ns: int,
        parent: Optional[Span] = None,
        attrs: Optional[dict] = None,
    ) -> Span:
        """Record an already-timed span (stage timers become spans)."""
        pid = parent.span_id if parent is not None else self._parent_id()
        s = Span(
            name, f"{self._prefix}-{next(self._seq)}", pid,
            start_ns, end_ns, attrs,
        )
        self.spans.append(s)
        return s

    def add_stage(
        self, name: str, dur_ns: int, attrs: Optional[dict] = None
    ) -> Span:
        """A stage measured as a duration ending now. Marked pretimed:
        the recording thread's active-span stack never held it, so the
        host profiler attributed those samples to the ENCLOSING span —
        :func:`stack_self_times` needs to tell the two apart."""
        end = now_ns()
        attrs = dict(attrs) if attrs else {}
        attrs.setdefault("pretimed", 1)
        return self.add_span(
            name, end - max(0, int(dur_ns)), end, attrs=attrs
        )

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def merge_remote(self, spans: list[dict], anchor: Optional[Span]) -> None:
        """Fold a remote segment's spans in, re-based so the segment root
        starts at `anchor` (the local rpc.call span) — remote monotonic
        clocks share no base with ours, but durations are trustworthy."""
        if not spans:
            return
        decoded = [Span.from_wire(d) for d in spans]
        # the segment root is the span whose parent is not in the segment
        ids = {s.span_id for s in decoded}
        root = next((s for s in decoded if s.parent_id not in ids), decoded[0])
        shift = (anchor.start_ns if anchor is not None else now_ns()) - root.start_ns
        for s in decoded:
            s.start_ns += shift
            s.end_ns += shift
            if s is root and anchor is not None:
                s.parent_id = anchor.span_id
            self.spans.append(s)

    def finish(self, status: str = "ok", record: bool = True) -> None:
        """End the root span and (idempotently) hand the trace to the
        global recorder's ring buffer."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
        if not self.root.end_ns:
            self.root.end_ns = now_ns()
        self.attrs.setdefault("status", status)
        if record and not self.remote:
            recorder().record(self)

    # -- wire ----------------------------------------------------------

    def to_wire(self) -> dict:
        # snapshot first: spans may still be appended concurrently
        spans = [s.to_wire() for s in list(self.spans)]
        end = self.root.end_ns or now_ns()
        return {
            "id": self.trace_id,
            "name": self.name,
            "attrs": dict(self.attrs),
            "start": self.root.start_ns,
            "end": end,
            "duration_ms": round((end - self.root.start_ns) / 1e6, 3),
            "spans": spans,
        }


# -- bounded ring buffer of finished traces -----------------------------


class TraceRecorder:
    def __init__(self, max_traces: int = 256) -> None:
        self._lock = threading.Lock()
        self.max_traces = max_traces
        # trace_id -> wire dict, insertion-ordered (oldest first)
        self._ring: dict[str, dict] = {}
        self.recorded = 0
        self.dropped = 0

    def configure(self, max_traces: int) -> None:
        with self._lock:
            self.max_traces = max(1, int(max_traces))
            while len(self._ring) > self.max_traces:
                self._evict_one_locked()

    def record(self, ctx: TraceContext) -> None:
        wire = ctx.to_wire()
        from . import metrics

        with self._lock:
            # same-id segments merge (a retried eval finishes twice, a
            # forwarded trace lands leader-side too): newest wins the
            # metadata, spans concatenate
            prev = self._ring.pop(ctx.trace_id, None)
            if prev is not None:
                wire["spans"] = prev["spans"] + wire["spans"]
                wire["start"] = min(wire["start"], prev["start"])
                wire["end"] = max(wire["end"], prev["end"])
                # duration must track the MERGED window, not the last
                # segment's own (a redelivered eval finishes twice)
                wire["duration_ms"] = round(
                    (wire["end"] - wire["start"]) / 1e6, 3
                )
            self._ring[ctx.trace_id] = wire
            self.recorded += 1
            while len(self._ring) > self.max_traces:
                self._evict_one_locked()
        metrics.incr("nomad.trace.recorded")

    def _evict_one_locked(self) -> None:
        """Drop the oldest trace of the MOST POPULATED trace name: a
        chatty name (per-write `http` traces under a job-update loop)
        must not flush the last `eval`/`tpu.batch` traces — the ones
        the surface exists to debug — out of the ring. With all names
        equally represented this degrades to plain drop-oldest."""
        counts: dict[str, int] = {}
        for t in self._ring.values():
            counts[t["name"]] = counts.get(t["name"], 0) + 1
        top = max(counts, key=counts.get)  # ties: oldest-inserted name
        victim = next(
            k for k, t in self._ring.items() if t["name"] == top
        )
        self._ring.pop(victim)
        self.dropped += 1

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            t = self._ring.get(trace_id)
            return dict(t) if t is not None else None

    def list(
        self,
        name: str = "",
        eval_id: str = "",
        job_id: str = "",
        limit: int = 50,
    ) -> list[dict]:
        """Newest-first summaries (no spans), filterable by trace name or
        eval/job id attrs (batch traces list eval ids in attrs)."""
        with self._lock:
            traces = list(self._ring.values())
        out = []
        for t in reversed(traces):
            a = t.get("attrs", {})
            if name and t.get("name") != name:
                continue
            if eval_id and eval_id != a.get("eval_id") and (
                eval_id not in (a.get("eval_ids") or ())
            ):
                continue
            if job_id and job_id != a.get("job_id") and (
                job_id not in (a.get("job_ids") or ())
            ):
                continue
            out.append(
                {
                    "id": t["id"],
                    "name": t["name"],
                    "attrs": a,
                    "start": t["start"],
                    "end": t["end"],
                    "duration_ms": t.get("duration_ms"),
                    "num_spans": len(t.get("spans", ())),
                }
            )
            if len(out) >= limit:
                break
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": len(self._ring),
                "recorded": self.recorded,
                "dropped": self.dropped,
                "max": self.max_traces,
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_recorder = TraceRecorder()
_recorder_metrics_handle = None


def recorder() -> TraceRecorder:
    return _recorder


def configure(max_traces: Optional[int] = None, enabled_: Optional[bool] = None) -> None:
    """Operator knob application (agent config / SIGHUP reload)."""
    global _recorder_metrics_handle
    if max_traces is not None:
        _recorder.configure(max_traces)
    if enabled_ is not None:
        set_enabled(enabled_)
    if _recorder_metrics_handle is None:
        from . import metrics

        _recorder_metrics_handle = metrics.register_provider(
            "nomad.trace", lambda: {
                k: float(v) for k, v in _recorder.stats().items()
            }
        )


# -- thread-local current context ---------------------------------------

_tls = threading.local()


def current() -> Optional[TraceContext]:
    return getattr(_tls, "ctx", None)


def set_current(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    # host-profiler span correlation: with no child span open yet, the
    # thread's work belongs to the trace ROOT (a solve running under
    # `use(ctx)` before any stage span opens must attribute to
    # "tpu.batch"/"bench.batch", not "-")
    tid = threading.get_ident()
    if ctx is None:
        _thread_spans.pop(tid, None)
    else:
        st = ctx._stack()
        _thread_spans[tid] = st[-1].name if st else ctx.name
    return prev


class _Use:
    """`with use(ctx):` — install ctx as the thread's current context.
    Re-entrant and cheap; ctx may be None (no-op)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]) -> None:
        self._ctx = ctx
        self._prev = None

    def __enter__(self) -> Optional[TraceContext]:
        if self._ctx is not None:
            self._prev = set_current(self._ctx)
        return self._ctx

    def __exit__(self, *exc) -> None:
        if self._ctx is not None:
            set_current(self._prev)


def use(ctx: Optional[TraceContext]) -> _Use:
    return _Use(ctx)


# -- hot-path helpers ----------------------------------------------------


def start_trace(name: str, **attrs) -> Optional[TraceContext]:
    """New trace when tracing is enabled; None (the no-op path) when not."""
    if not _enabled:
        return None
    return TraceContext(name, attrs=attrs)


def span(
    ctx: Optional[TraceContext],
    name: str,
    parent: Optional[Span] = None,
    **attrs,
):
    """Open a child span on ctx, or the singleton no-op when ctx is None."""
    if ctx is None:
        return NOOP_SPAN
    return ctx.span(name, parent=parent, **attrs)


def stage(name: str, dur_ns: int) -> None:
    """Record a pre-timed stage onto the CURRENT context, if any — the
    solver's existing stage timers become spans through this single
    call, and the disabled path is one flag test + one getattr."""
    if not _enabled:
        return
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.add_stage(name, dur_ns)


def stage_attrs(name: str, dur_ns: int, **attrs) -> None:
    """:func:`stage` with span attributes — the solver-observability
    spans (solver.compile carries the kernel + shape signature,
    solver.transfer the direction + byte count). Same no-op discipline:
    one flag test + one getattr when tracing is off."""
    if not _enabled:
        return
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.add_stage(name, dur_ns, attrs=attrs)


# -- wire helpers for the RPC envelope -----------------------------------
# (the envelope FIELD NAMES live in rpc/wire.py TRACE_KEY/TRACE_SPANS_KEY,
# beside the rest of the framing constants — one source of truth)


def wire_ref(ctx: TraceContext, parent: Optional[Span] = None) -> dict:
    return {
        "id": ctx.trace_id,
        "parent": parent.span_id if parent is not None else ctx.root.span_id,
    }


def open_segment(name: str, ref: dict) -> TraceContext:
    """Server side of an RPC hop: a remote segment of the caller's trace.
    Its spans travel back in the response; it never lands in the local
    ring (the originator owns the stitched trace)."""
    return TraceContext(
        name,
        trace_id=str(ref.get("id", "")),
        parent_id=str(ref.get("parent", "")),
        remote=True,
    )


# -- analysis: span trees, self-times, critical path ---------------------


def _interval_union_ns(intervals: list[tuple[int, int]]) -> int:
    """Total ns covered by the union of [start, end) intervals."""
    total = 0
    last_end = None
    for s, e in sorted(intervals):
        if e <= s:
            continue
        if last_end is None or s >= last_end:
            total += e - s
            last_end = e
        elif e > last_end:
            total += e - last_end
            last_end = e
    return total


def children_of(trace: dict) -> dict[str, list[dict]]:
    """parent span id -> [child span wire dicts], stable span order."""
    kids: dict[str, list[dict]] = {}
    for s in trace.get("spans", ()):
        if s.get("parent"):
            kids.setdefault(s["parent"], []).append(s)
    return kids


def trace_roots(trace: dict) -> list[dict]:
    ids = {s["id"] for s in trace.get("spans", ())}
    return [
        s for s in trace.get("spans", ()) if s.get("parent", "") not in ids
    ]


def self_times(trace: dict) -> dict[str, int]:
    """Span name -> total SELF time ns across the trace: duration minus
    the union of child intervals (union, not sum — pipelined children
    overlap and a plain sum would go negative)."""
    kids = children_of(trace)
    out: dict[str, int] = {}
    for s in trace.get("spans", ()):
        dur = max(0, s["end"] - s["start"])
        child_cover = _interval_union_ns(
            [
                (max(c["start"], s["start"]), min(c["end"], s["end"]))
                for c in kids.get(s["id"], ())
            ]
        )
        out[s["name"]] = out.get(s["name"], 0) + max(0, dur - child_cover)
    return out


def stack_self_times(trace: dict) -> dict[str, int]:
    """:func:`self_times` over the STACK-PARENTED spans only: pre-timed
    stage spans (``add_stage`` — host_prep, readback, materialize, the
    solver.compile/transfer attributions) are dropped before the child-
    interval subtraction. This is the trace-side quantity comparable to
    the host profiler's span attribution: a sampler attributes the
    wall time of a pre-timed stage to the span the recording thread had
    OPEN (the stage never pushed the stack), so plain self_times — which
    subtracts the stage from its parent — would disagree with the
    profiler by exactly the stage's duration (bench span-agreement,
    docs/profiling.md)."""
    spans = [
        s for s in trace.get("spans", ())
        if not (s.get("attrs") or {}).get("pretimed")
    ]
    return self_times({**trace, "spans": spans})


def coverage(trace: dict) -> float:
    """Fraction of the root span's wall time covered by the union of its
    direct children — the 'named spans account for X% of wall time'
    metric the e2e acceptance gate checks."""
    roots = trace_roots(trace)
    if not roots:
        return 0.0
    root = roots[0]
    dur = max(1, root["end"] - root["start"])
    kids = children_of(trace).get(root["id"], ())
    covered = _interval_union_ns(
        [
            (max(c["start"], root["start"]), min(c["end"], root["end"]))
            for c in kids
        ]
    )
    return covered / dur


def critical_path(traces: list[dict], top: int = 5) -> list[tuple[str, int]]:
    """Top span names by total self-time across the given traces — the
    'where does wall time actually go' summary `operator trace` prints."""
    totals: dict[str, int] = {}
    for t in traces:
        for name, ns in self_times(t).items():
            totals[name] = totals.get(name, 0) + ns
    return sorted(totals.items(), key=lambda kv: -kv[1])[:top]


def render_tree(trace: dict) -> str:
    """ASCII span tree with durations and self-times (CLI + tests)."""
    kids = children_of(trace)
    selfs = self_times(trace)
    lines: list[str] = []
    dur_ms = trace.get("duration_ms")
    header = (
        f"TRACE {trace['id']} {trace.get('name', '')} "
        f"{dur_ms if dur_ms is not None else '?'}ms"
    )
    attrs = trace.get("attrs") or {}
    if attrs:
        compact = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        header += f"  [{compact}]"
    lines.append(header)

    def walk(s: dict, prefix: str, last: bool) -> None:
        dur = (s["end"] - s["start"]) / 1e6
        own = [
            c for c in kids.get(s["id"], ())
        ]
        # per-span self time: duration minus union of ITS children
        cover = _interval_union_ns(
            [
                (max(c["start"], s["start"]), min(c["end"], s["end"]))
                for c in own
            ]
        )
        self_ms = max(0, (s["end"] - s["start"]) - cover) / 1e6
        branch = "└─ " if last else "├─ "
        extra = ""
        shown = {
            k: v for k, v in (s.get("attrs") or {}).items()
            if k != "pretimed"  # bookkeeping marker, not operator signal
        }
        if shown:
            extra = "  " + " ".join(
                f"{k}={v}" for k, v in sorted(shown.items())
            )
        lines.append(
            f"{prefix}{branch}{s['name']:<24} {dur:9.3f}ms"
            f"  (self {self_ms:.3f}ms){extra}"
        )
        child_prefix = prefix + ("   " if last else "│  ")
        for i, c in enumerate(own):
            walk(c, child_prefix, i == len(own) - 1)

    roots = trace_roots(trace)
    for i, r in enumerate(roots):
        walk(r, "", i == len(roots) - 1)
    if selfs:
        lines.append("")
        lines.append("top self-time:")
        for name, ns in critical_path([trace], top=5):
            lines.append(f"  {name:<28} {ns / 1e6:9.3f}ms")
    return "\n".join(lines)
