"""Mock object factories used by every layer's tests.

Reference: nomad/mock/mock.go — Node():14, Job():232, Alloc():1277,
Eval():1216. Shapes chosen to mirror the reference's defaults (4000MHz/8GB
nodes, 500MHz/256MB web tasks) so differential benchmarks are comparable.
"""

from __future__ import annotations

import itertools

from ..structs import (
    Affinity,
    AllocatedResources,
    AllocatedTaskResources,
    Allocation,
    Constraint,
    Evaluation,
    Job,
    NetworkResource,
    Node,
    NodeResources,
    Port,
    Resources,
    Task,
    TaskGroup,
    UpdateStrategy,
    alloc_name,
    generate_uuid,
    now_ns,
)
from ..structs.structs import (
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_STATUS_PENDING,
    JOB_TYPE_BATCH,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSBATCH,
    JOB_TYPE_SYSTEM,
    NODE_STATUS_READY,
    DriverInfo,
    NodeDeviceInstance,
    NodeDeviceResource,
)
from ..structs.node_class import compute_node_class

_counter = itertools.count()


def node(**overrides) -> Node:
    i = next(_counter)
    n = Node(
        id=generate_uuid(),
        name=f"node-{i}",
        datacenter="dc1",
        node_class="linux-medium-pci",
        attributes={
            "kernel.name": "linux",
            "arch": "x86",
            "nomad.version": "1.2.0",
            "driver.exec": "1",
            "driver.mock": "1",
            "cpu.frequency": "2800",
            "cpu.numcores": "4",
        },
        resources=NodeResources(
            cpu=4000,
            memory_mb=8192,
            disk_mb=100 * 1024,
            total_cores=4,
            networks=[
                NetworkResource(
                    device="eth0", cidr="192.168.0.100/32", ip="192.168.0.100", mbits=1000
                )
            ],
        ),
        drivers={
            "mock": DriverInfo(detected=True, healthy=True),
            "exec": DriverInfo(detected=True, healthy=True),
        },
        status=NODE_STATUS_READY,
    )
    for k, v in overrides.items():
        setattr(n, k, v)
    n.canonicalize()
    n.computed_class = compute_node_class(n)
    return n


def tpu_node(**overrides) -> Node:
    """A node advertising an accelerator device group (the reference's
    NvidiaNode :131 analog, retargeted at TPUs)."""
    n = node(**overrides)
    n.resources.devices = [
        NodeDeviceResource(
            vendor="google",
            type="tpu",
            name="v5e",
            instances=[NodeDeviceInstance(id=f"tpu-{i}", healthy=True) for i in range(4)],
            attributes={"hbm_gib": 16},
        )
    ]
    n.computed_class = compute_node_class(n)
    return n


def _web_task() -> Task:
    return Task(
        name="web",
        driver="mock",
        config={"run_for": "0s"},
        env={"FOO": "bar"},
        resources=Resources(
            cpu=500,
            memory_mb=256,
            networks=[NetworkResource(mbits=50, dynamic_ports=[Port(label="http")])],
        ),
    )


def job(**overrides) -> Job:
    i = next(_counter)
    j = Job(
        id=f"mock-service-{generate_uuid()[:8]}-{i}",
        name="my-job",
        type=JOB_TYPE_SERVICE,
        priority=50,
        all_at_once=False,
        datacenters=["dc1"],
        constraints=[Constraint(ltarget="${attr.kernel.name}", rtarget="linux", operand="=")],
        task_groups=[
            TaskGroup(
                name="web",
                count=10,
                tasks=[_web_task()],
            )
        ],
        update=UpdateStrategy(
            stagger_s=30,
            max_parallel=5,
            health_check="checks",
            min_healthy_time_s=10,
            healthy_deadline_s=300,
            progress_deadline_s=600,
        ),
        status="pending",
        version=0,
        create_index=42,
        modify_index=99,
        job_modify_index=99,
    )
    for k, v in overrides.items():
        setattr(j, k, v)
    j.canonicalize()
    return j


def batch_job(**overrides) -> Job:
    j = job(**overrides)
    if "type" not in overrides:
        j.type = JOB_TYPE_BATCH
    if "id" not in overrides:
        j.id = f"mock-batch-{generate_uuid()[:8]}"
    j.update = None
    for tg in j.task_groups:
        tg.update = None
        tg.reschedule_policy = None
        tg.count = 1
        for t in tg.tasks:
            t.resources.networks = []
    j.canonicalize()
    return j


def system_job(**overrides) -> Job:
    j = job(**overrides)
    if "type" not in overrides:
        j.type = JOB_TYPE_SYSTEM
    if "id" not in overrides:
        j.id = f"mock-system-{generate_uuid()[:8]}"
    j.update = None
    for tg in j.task_groups:
        tg.count = 1
        tg.update = None
        tg.reschedule_policy = None
    j.canonicalize()
    return j


def sysbatch_job(**overrides) -> Job:
    j = system_job(**overrides)
    j.type = JOB_TYPE_SYSBATCH
    if "id" not in overrides:
        j.id = f"mock-sysbatch-{generate_uuid()[:8]}"
    return j


def affinity_job(**overrides) -> Job:
    j = job(**overrides)
    j.affinities = [
        Affinity(ltarget="${node.datacenter}", rtarget="dc1", operand="=", weight=100)
    ]
    return j


def alloc(job_: Job | None = None, node_: Node | None = None, index: int = 0, **overrides) -> Allocation:
    j = job_ if job_ is not None else job()
    tg = j.task_groups[0]
    a = Allocation(
        id=generate_uuid(),
        namespace=j.namespace,
        eval_id=generate_uuid(),
        name=alloc_name(j.id, tg.name, index),
        node_id=node_.id if node_ is not None else "",
        job_id=j.id,
        job=j,
        task_group=tg.name,
        resources=AllocatedResources(
            tasks={
                t.name: AllocatedTaskResources(
                    cpu=t.resources.cpu, memory_mb=t.resources.memory_mb
                )
                for t in tg.tasks
            },
            shared_disk_mb=tg.ephemeral_disk.size_mb,
        ),
        desired_status="run",
        client_status="pending",
        create_time=now_ns(),
        modify_time=now_ns(),
    )
    for k, v in overrides.items():
        setattr(a, k, v)
    return a


def evaluation(**overrides) -> Evaluation:
    e = Evaluation(
        id=generate_uuid(),
        priority=50,
        type=JOB_TYPE_SERVICE,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
        job_id=generate_uuid(),
        status=EVAL_STATUS_PENDING,
        create_time=now_ns(),
        modify_time=now_ns(),
    )
    for k, v in overrides.items():
        setattr(e, k, v)
    return e


def eval_for_job(j: Job, **overrides) -> Evaluation:
    return evaluation(
        job_id=j.id,
        namespace=j.namespace,
        type=j.type,
        priority=j.priority,
        job_modify_index=j.job_modify_index,
        **overrides,
    )
