from .factories import (
    alloc,
    batch_job,
    eval_for_job,
    evaluation,
    job,
    node,
    system_job,
    sysbatch_job,
    tpu_node,
)
