"""In-process metrics registry + /v1/metrics surface.

Reference: command/agent/command.go:979 setupTelemetry (go-metrics
InmemSink behind /v1/metrics) and the server gauges published from
nomad/server.go:444-450 (broker ready/unacked, plan-queue depth) plus the
per-eval invoke latencies emitted by the workers.

Design: one process-global registry with three primitives —

  * counters   (monotonic; incr)
  * gauges     (last value; set_gauge, or a registered PROVIDER callback
                sampled at snapshot time, which is how subsystems that
                already keep live stats — the eval broker, the plan
                queue — are surfaced without double bookkeeping)
  * samples    (observe: count/sum/min/max/last — enough for rates and
                latencies without a histogram dependency)

Everything is threadsafe and cheap enough for hot paths (a dict update
under a lock); the snapshot is what the HTTP endpoint serves.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

_START = time.time()


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._samples: dict[str, dict[str, float]] = {}
        # name -> stack of (handle, fn): multiple instances (in-process
        # test clusters) may register the same name; the newest wins the
        # snapshot and unregistering by handle restores the previous one
        # instead of deleting a survivor's provider.
        self._providers: dict[str, list[tuple[object, Callable]]] = {}

    # -- write side ----------------------------------------------------

    def incr(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        """Record one sample (e.g. a latency in seconds)."""
        with self._lock:
            s = self._samples.get(name)
            if s is None:
                self._samples[name] = {
                    "count": 1, "sum": value, "min": value,
                    "max": value, "last": value,
                }
            else:
                s["count"] += 1
                s["sum"] += value
                s["min"] = min(s["min"], value)
                s["max"] = max(s["max"], value)
                s["last"] = value

    def time_ns(self, name: str, ns: int) -> None:
        self.observe(name, ns / 1e9)

    def register_provider(
        self, name: str, fn: Callable[[], dict[str, float]]
    ) -> object:
        """Sample a subsystem's live stats at snapshot time. The callback
        returns {suffix: value}; published as gauges under name.suffix.
        Returns a handle for unregister_provider."""
        handle = object()
        with self._lock:
            self._providers.setdefault(name, []).append((handle, fn))
        return handle

    def unregister_provider(self, name: str, handle: object = None) -> None:
        """Remove a provider. With a handle, removes exactly that
        registration (other instances under the same name survive);
        without one, removes the newest."""
        with self._lock:
            stack = self._providers.get(name)
            if not stack:
                return
            if handle is None:
                stack.pop()
            else:
                self._providers[name] = [
                    (h, f) for h, f in stack if h is not handle
                ]
            if not self._providers[name]:
                del self._providers[name]

    # -- read side -----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            samples = {k: dict(v) for k, v in self._samples.items()}
            providers = {
                name: stack[-1][1]
                for name, stack in self._providers.items()
                if stack
            }
        for name, fn in providers.items():
            try:
                for suffix, value in (fn() or {}).items():
                    gauges[f"{name}.{suffix}"] = value
            except Exception:
                gauges[f"{name}.error"] = 1
        for s in samples.values():
            s["mean"] = s["sum"] / s["count"] if s["count"] else 0.0
        return {
            "uptime_seconds": round(time.time() - _START, 3),
            "counters": counters,
            "gauges": gauges,
            "samples": samples,
        }

    def prometheus_text(self) -> str:
        """The registry in Prometheus text exposition format (0.0.4) —
        what a stock Prometheus scrapes from /v1/metrics?format=prometheus
        (reference: command/agent/command.go:979-1036 wires a prometheus
        sink beside the inmem one).

        counters → <name>_total counter; gauges → gauge; samples →
        summary (_count/_sum) with min/max/last as companion gauges."""
        snap = self.snapshot()
        lines: list[str] = []

        def emit(name: str, kind: str, value: float) -> None:
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {_prom_value(value)}")

        emit("nomad_uptime_seconds", "gauge", snap["uptime_seconds"])
        for name, v in sorted(snap["counters"].items()):
            emit(_prom_name(name) + "_total", "counter", v)
        for name, v in sorted(snap["gauges"].items()):
            emit(_prom_name(name), "gauge", v)
        for name, s in sorted(snap["samples"].items()):
            n = _prom_name(name)
            lines.append(f"# TYPE {n} summary")
            lines.append(f"{n}_sum {_prom_value(s['sum'])}")
            lines.append(f"{n}_count {_prom_value(s['count'])}")
            for stat in ("min", "max", "last"):
                emit(f"{n}_{stat}", "gauge", s[stat])
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Test helper: forget everything (providers included)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._samples.clear()
            self._providers.clear()


_global = Registry()


def registry() -> Registry:
    return _global


# Module-level conveniences: the hot paths call these directly.
incr = _global.incr
set_gauge = _global.set_gauge
observe = _global.observe
time_ns = _global.time_ns
register_provider = _global.register_provider
unregister_provider = _global.unregister_provider
snapshot = _global.snapshot
prometheus_text = _global.prometheus_text


import re as _re


def _prom_name(name: str) -> str:
    out = _re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class StatsdSink:
    """Push-mode telemetry: periodically emits the registry to a statsd
    daemon over UDP (reference: command/agent/command.go:1002 wires
    statsd_address into a go-metrics fanout sink).

    gauges ride as |g; counters as |c DELTAS since the last push (statsd
    counters are rate-counters, so a monotonic total must be
    differenced); sample counts/sums as |g so dashboards can rate() them.
    """

    def __init__(self, address: str, interval_s: float = 10.0,
                 reg: Optional[Registry] = None) -> None:
        import socket

        host, sep, port = address.rpartition(":")
        if not sep or not port.isdigit():
            raise ValueError(
                f"statsd_address must be host:port, got {address!r}"
            )
        self.addr = (host.strip("[]") or "127.0.0.1", int(port))
        # a zero/negative interval would busy-loop the sink thread
        self.interval_s = max(1.0, float(interval_s))
        self.reg = reg or _global
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._last_counters: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="statsd-sink"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        self._sock.close()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.push_once()
            except OSError:
                pass  # daemon away; keep trying

    def _decorate(self, line: str) -> str:
        """Per-line hook for dialect extensions (DogStatsD tags)."""
        return line

    def push_once(self) -> int:
        snap = self.reg.snapshot()
        lines: list[str] = []
        for name, v in snap["counters"].items():
            delta = v - self._last_counters.get(name, 0)
            self._last_counters[name] = v
            if delta:
                lines.append(self._decorate(
                    f"{_prom_name(name)}:{_prom_value(delta)}|c"))
        for name, v in snap["gauges"].items():
            lines.append(self._decorate(
                f"{_prom_name(name)}:{_prom_value(v)}|g"))
        for name, s in snap["samples"].items():
            n = _prom_name(name)
            lines.append(self._decorate(
                f"{n}.count:{_prom_value(s['count'])}|g"))
            lines.append(self._decorate(
                f"{n}.sum:{_prom_value(s['sum'])}|g"))
        sent = 0
        buf: list[str] = []
        size = 0
        for line in lines:
            if size + len(line) > 1400 and buf:  # stay under typical MTU
                self._sock.sendto("\n".join(buf).encode(), self.addr)
                sent += len(buf)
                buf, size = [], 0
            buf.append(line)
            size += len(line) + 1
        if buf:
            self._sock.sendto("\n".join(buf).encode(), self.addr)
            sent += len(buf)
        return sent


class DatadogSink(StatsdSink):
    """DogStatsD flavor of the statsd push (reference:
    command/agent/command.go:1010 wires datadog_address into a
    datadog.NewDogStatsdSink): same wire protocol plus |#tag:value
    annotations. Constant tags (node name, region, datacenter) ride on
    every metric, which is how the reference's DogStatsd sink attaches
    its host tags."""

    def __init__(self, address: str, interval_s: float = 10.0,
                 reg: Optional[Registry] = None,
                 tags: Optional[dict] = None) -> None:
        super().__init__(address, interval_s, reg)
        self._suffix = ""
        if tags:
            joined = ",".join(f"{k}:{v}" for k, v in sorted(tags.items()))
            self._suffix = f"|#{joined}"

    def _decorate(self, line: str) -> str:
        return line + self._suffix
