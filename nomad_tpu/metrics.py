"""In-process metrics registry + /v1/metrics surface.

Reference: command/agent/command.go:979 setupTelemetry (go-metrics
InmemSink behind /v1/metrics) and the server gauges published from
nomad/server.go:444-450 (broker ready/unacked, plan-queue depth) plus the
per-eval invoke latencies emitted by the workers.

Design: one process-global registry with three primitives —

  * counters   (monotonic; incr)
  * gauges     (last value; set_gauge, or a registered PROVIDER callback
                sampled at snapshot time, which is how subsystems that
                already keep live stats — the eval broker, the plan
                queue — are surfaced without double bookkeeping)
  * samples    (observe: count/sum/min/max/last — enough for rates and
                latencies without a histogram dependency)

Everything is threadsafe and cheap enough for hot paths (a dict update
under a lock); the snapshot is what the HTTP endpoint serves.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

_START = time.time()


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._samples: dict[str, dict[str, float]] = {}
        # name -> stack of (handle, fn): multiple instances (in-process
        # test clusters) may register the same name; the newest wins the
        # snapshot and unregistering by handle restores the previous one
        # instead of deleting a survivor's provider.
        self._providers: dict[str, list[tuple[object, Callable]]] = {}

    # -- write side ----------------------------------------------------

    def incr(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        """Record one sample (e.g. a latency in seconds)."""
        with self._lock:
            s = self._samples.get(name)
            if s is None:
                self._samples[name] = {
                    "count": 1, "sum": value, "min": value,
                    "max": value, "last": value,
                }
            else:
                s["count"] += 1
                s["sum"] += value
                s["min"] = min(s["min"], value)
                s["max"] = max(s["max"], value)
                s["last"] = value

    def time_ns(self, name: str, ns: int) -> None:
        self.observe(name, ns / 1e9)

    def register_provider(
        self, name: str, fn: Callable[[], dict[str, float]]
    ) -> object:
        """Sample a subsystem's live stats at snapshot time. The callback
        returns {suffix: value}; published as gauges under name.suffix.
        Returns a handle for unregister_provider."""
        handle = object()
        with self._lock:
            self._providers.setdefault(name, []).append((handle, fn))
        return handle

    def unregister_provider(self, name: str, handle: object = None) -> None:
        """Remove a provider. With a handle, removes exactly that
        registration (other instances under the same name survive);
        without one, removes the newest."""
        with self._lock:
            stack = self._providers.get(name)
            if not stack:
                return
            if handle is None:
                stack.pop()
            else:
                self._providers[name] = [
                    (h, f) for h, f in stack if h is not handle
                ]
            if not self._providers[name]:
                del self._providers[name]

    # -- read side -----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            samples = {k: dict(v) for k, v in self._samples.items()}
            providers = {
                name: stack[-1][1]
                for name, stack in self._providers.items()
                if stack
            }
        for name, fn in providers.items():
            try:
                for suffix, value in (fn() or {}).items():
                    gauges[f"{name}.{suffix}"] = value
            except Exception:
                gauges[f"{name}.error"] = 1
        for s in samples.values():
            s["mean"] = s["sum"] / s["count"] if s["count"] else 0.0
        return {
            "uptime_seconds": round(time.time() - _START, 3),
            "counters": counters,
            "gauges": gauges,
            "samples": samples,
        }

    def reset(self) -> None:
        """Test helper: forget everything (providers included)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._samples.clear()
            self._providers.clear()


_global = Registry()


def registry() -> Registry:
    return _global


# Module-level conveniences: the hot paths call these directly.
incr = _global.incr
set_gauge = _global.set_gauge
observe = _global.observe
time_ns = _global.time_ns
register_provider = _global.register_provider
unregister_provider = _global.unregister_provider
snapshot = _global.snapshot
