"""In-process metrics registry + /v1/metrics surface.

Reference: command/agent/command.go:979 setupTelemetry (go-metrics
InmemSink behind /v1/metrics) and the server gauges published from
nomad/server.go:444-450 (broker ready/unacked, plan-queue depth) plus the
per-eval invoke latencies emitted by the workers.

Design: one process-global registry with three primitives —

  * counters    (monotonic; incr)
  * gauges      (last value; set_gauge, or a registered PROVIDER callback
                 sampled at snapshot time, which is how subsystems that
                 already keep live stats — the eval broker, the plan
                 queue — are surfaced without double bookkeeping)
  * histograms  (observe: fixed-boundary exponential buckets + count/sum/
                 min/max/last, with an InmemSink-style ring of per-interval
                 snapshots so the surface can answer both "p99 since boot"
                 and "p99 right now" — the cumulative vs last-window split
                 that separates "slow now" from "slow once at startup")

Everything is threadsafe and cheap enough for hot paths: an observe is a
bucket index (bisect over ~50 precomputed bounds) plus bounded array
increments under the registry lock — no allocation on the steady path
(interval rotation allocates one fresh bucket array per interval).
Percentiles are computed at SNAPSHOT time from the bucket counts, never
on the write path.

Exported three ways: the JSON snapshot (/v1/metrics) carries
p50/p90/p95/p99 per name cumulative and for the last window;
?format=prometheus serves real histogram exposition (_bucket{le=...},
_sum, _count); the statsd/DogStatsD sinks forward raw observations as
|ms timings (captured in a bounded side buffer only while a sink is
attached — zero cost otherwise).
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_right
from collections import deque
from math import inf
from typing import Callable, Optional

_START = time.time()

# Fixed exponential boundaries (seconds): 100us .. ~1678s at factor
# sqrt(2) — ~49 buckets plus +Inf. Exponential spacing keeps relative
# quantile-interpolation error bounded (<~20% per bucket) across seven
# decades of latency, the same shape Prometheus client libraries and
# go-metrics' bucketed sinks use. Fixed (not per-name) boundaries keep
# observe() a branch-free bisect and make buckets aggregatable across
# processes.
DEFAULT_BOUNDS: tuple[float, ...] = tuple(
    round(1e-4 * 2 ** (i / 2.0), 10) for i in range(49)
)
DEFAULT_INTERVAL_S = 10.0
DEFAULT_RING = 6  # with 10s intervals: the last minute
QUANTILES = (("p50", 0.50), ("p90", 0.90), ("p95", 0.95), ("p99", 0.99))


def _bucket_quantile(
    bounds: tuple, counts, total: float, q: float, vmin: float, vmax: float
) -> float:
    """Quantile estimate from bucket counts: linear interpolation inside
    the covering bucket (Prometheus histogram_quantile semantics), with
    the open-ended buckets clamped to the observed min/max so a
    single-bucket distribution reports sane values."""
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if not c:
            continue
        if cum + c >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else vmax
            v = lo + (hi - lo) * ((rank - cum) / c)
            return min(max(v, vmin), vmax)
        cum += c
    return vmax


class Histogram:
    """One metric's distribution: cumulative bucket counts + a bounded
    ring of per-interval snapshots (go-metrics InmemSink shape). All
    mutation happens under the owning Registry's lock; rotated interval
    entries are immutable by construction (rotation hands off the live
    array and allocates a fresh one), so snapshot readers may share
    them without copying."""

    __slots__ = (
        "bounds", "counts", "count", "sum", "min", "max", "last",
        "interval_s", "ring",
        "cur_counts", "cur_count", "cur_sum", "cur_min", "cur_max",
        "cur_start",
    )

    def __init__(
        self, bounds: tuple, interval_s: float, ring_len: int, now: float
    ) -> None:
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = inf
        self.max = -inf
        self.last = 0.0
        self.interval_s = interval_s
        # (start, end, counts, count, sum, min, max) per completed
        # interval; deque(maxlen) IS the eviction bound
        self.ring: deque = deque(maxlen=max(1, int(ring_len)))
        self._fresh_interval(now)

    def _fresh_interval(self, now: float) -> None:
        self.cur_counts = [0] * (len(self.bounds) + 1)
        self.cur_count = 0
        self.cur_sum = 0.0
        self.cur_min = inf
        self.cur_max = -inf
        self.cur_start = now

    def maybe_rotate(self, now: float) -> None:
        if now - self.cur_start < self.interval_s:
            return
        if self.cur_count:
            # end is capped at the interval boundary, not `now`: every
            # observation in this entry predates the boundary (a later
            # one would have rotated first), and a read-time rotation
            # long after traffic stopped must not stamp the stale burst
            # as just-finished (window age_s would read 0)
            self.ring.append((
                self.cur_start, self.cur_start + self.interval_s,
                self.cur_counts, self.cur_count,
                self.cur_sum, self.cur_min, self.cur_max,
            ))
        # idle gaps collapse: the next interval starts now, not on a
        # fixed grid — empty intervals are never ring entries
        self._fresh_interval(now)

    def observe(self, value: float, now: float) -> None:
        self.maybe_rotate(now)
        i = bisect_right(self.bounds, value)
        self.counts[i] += 1
        self.cur_counts[i] += 1
        self.count += 1
        self.cur_count += 1
        self.sum += value
        self.cur_sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        if value < self.cur_min:
            self.cur_min = value
        if value > self.cur_max:
            self.cur_max = value
        self.last = value

    # -- read side (called on copies/under lock by Registry.snapshot) --

    def raw(self, now: float) -> dict:
        """Copy of the mutable state, taken under the registry lock so
        percentile math can run outside it. Ring entries are immutable
        and shared; only the live arrays are copied.

        Rotates first (the caller holds the registry lock): rotation
        otherwise only happens inside observe(), so a metric whose
        traffic STOPPED would keep presenting its last burst as the
        live interval forever — age_s 0, 'slow now' — which is exactly
        the slow-now/slow-once confusion the window exists to kill."""
        self.maybe_rotate(now)
        win = None
        if self.cur_count:
            win = (
                self.cur_start, now, list(self.cur_counts),
                self.cur_count, self.cur_sum, self.cur_min, self.cur_max,
            )
        else:
            for entry in reversed(self.ring):
                if entry[3]:
                    win = entry
                    break
        return {
            "counts": list(self.counts),
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "last": self.last,
            "window": win,
        }


def _hist_stats(bounds: tuple, raw: dict, now: float) -> dict:
    """The JSON-snapshot entry for one histogram: back-compat
    count/sum/min/max/mean/last plus cumulative and last-window
    percentiles."""
    count = raw["count"]
    vmin = raw["min"] if count else 0.0
    vmax = raw["max"] if count else 0.0
    out = {
        "count": count,
        "sum": raw["sum"],
        "min": vmin,
        "max": vmax,
        "last": raw["last"],
        "mean": raw["sum"] / count if count else 0.0,
    }
    for key, q in QUANTILES:
        out[key] = _bucket_quantile(
            bounds, raw["counts"], count, q, vmin, vmax
        )
    win = raw["window"]
    if win is not None:
        ws, we, wcounts, wcount, wsum, wmin, wmax = win
        w = {
            "count": wcount,
            "mean": wsum / wcount if wcount else 0.0,
            "min": wmin if wcount else 0.0,
            "max": wmax if wcount else 0.0,
            "age_s": round(max(0.0, now - we), 3),
            "interval_s": round(we - ws, 3),
        }
        for key, q in QUANTILES:
            w[key] = _bucket_quantile(
                bounds, wcounts, wcount, q, wmin, wmax
            )
        out["window"] = w
    return out


class Registry:
    def __init__(
        self,
        bounds: tuple = DEFAULT_BOUNDS,
        interval_s: float = DEFAULT_INTERVAL_S,
        ring: int = DEFAULT_RING,
        histograms: bool = True,
    ) -> None:
        """histograms=False keeps the pre-histogram count/sum sample
        path — the bench comparator the 0.95x throughput gate measures
        the histogram path against (tests/test_metrics.py)."""
        # Lock-wait-attributed (hostobs.TimedLock): the registry lock is
        # the process's hottest shared lock — every observe/incr from
        # every subsystem serializes here. histogram=False is REQUIRED:
        # recording a wait via metrics.observe would re-acquire this
        # very lock (self-deadlock); the wait ledger rides the
        # /v1/profile/status locks table instead. Deferred import:
        # hostobs is a leaf that lazily imports metrics back.
        from .hostobs import TimedLock

        self._lock = TimedLock(
            "metrics_registry", threading.Lock(), histogram=False
        )
        self._bounds = tuple(bounds)
        self._interval_s = max(0.01, float(interval_s))
        self._ring_len = max(1, int(ring))
        self._histograms = bool(histograms)
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, Histogram] = {}
        self._samples: dict[str, dict[str, float]] = {}  # legacy mode
        # raw-observation side buffers for push sinks (statsd |ms
        # timings), PER CONSUMER handle — two sinks (statsd + datadog)
        # each get every observation instead of racing one shared
        # buffer's destructive drain. Empty = off (the default):
        # observe() pays one truthiness test. Bounded per name per
        # drain interval.
        self._timing_sinks: dict[object, dict[str, list[float]]] = {}
        self._timings_cap = 256
        self._timings_dropped = 0
        # name -> stack of (handle, fn): multiple instances (in-process
        # test clusters) may register the same name; the newest wins the
        # snapshot and unregistering by handle restores the previous one
        # instead of deleting a survivor's provider.
        self._providers: dict[str, list[tuple[object, Callable]]] = {}

    # -- write side ----------------------------------------------------

    def incr(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        """Record one sample (e.g. a latency in seconds)."""
        with self._lock:
            if self._timing_sinks:
                for bufs in self._timing_sinks.values():
                    buf = bufs.get(name)
                    if buf is None:
                        buf = bufs[name] = []
                    if len(buf) < self._timings_cap:
                        buf.append(value)
                    else:
                        self._timings_dropped += 1
            if not self._histograms:
                s = self._samples.get(name)
                if s is None:
                    self._samples[name] = {
                        "count": 1, "sum": value, "min": value,
                        "max": value, "last": value,
                    }
                else:
                    s["count"] += 1
                    s["sum"] += value
                    s["min"] = min(s["min"], value)
                    s["max"] = max(s["max"], value)
                    s["last"] = value
                return
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(
                    self._bounds, self._interval_s, self._ring_len,
                    time.monotonic(),
                )
            h.observe(value, time.monotonic())

    def time_ns(self, name: str, ns: int) -> None:
        self.observe(name, ns / 1e9)

    def configure_windows(
        self, interval_s: Optional[float] = None, ring: Optional[int] = None
    ) -> None:
        """Operator knob (telemetry { collection_interval }): window
        width/ring depth for histograms created AFTER the call; existing
        histograms keep their interval (cheap, and windows stay
        comparable within one name's ring)."""
        with self._lock:
            if interval_s is not None:
                self._interval_s = max(0.01, float(interval_s))
            if ring is not None:
                self._ring_len = max(1, int(ring))

    # -- push-sink timing capture --------------------------------------

    def enable_timing_capture(self, cap: int = 256) -> object:
        """Register a timing consumer; returns the handle its drains
        and disable use. Each consumer sees every observation."""
        handle = object()
        with self._lock:
            self._timing_sinks[handle] = {}
            self._timings_cap = max(1, int(cap))
        return handle

    def disable_timing_capture(self, handle: object) -> None:
        with self._lock:
            self._timing_sinks.pop(handle, None)

    def drain_timings(self, handle: object) -> dict[str, list[float]]:
        """One consumer's raw observations since its last drain (push
        sinks forward them as statsd |ms timings)."""
        with self._lock:
            buf = self._timing_sinks.get(handle)
            if not buf:
                return {}
            self._timing_sinks[handle] = {}
            return buf

    def register_provider(
        self, name: str, fn: Callable[[], dict[str, float]]
    ) -> object:
        """Sample a subsystem's live stats at snapshot time. The callback
        returns {suffix: value}; published as gauges under name.suffix.
        Returns a handle for unregister_provider."""
        handle = object()
        with self._lock:
            self._providers.setdefault(name, []).append((handle, fn))
        return handle

    def unregister_provider(self, name: str, handle: object = None) -> None:
        """Remove a provider. With a handle, removes exactly that
        registration (other instances under the same name survive);
        without one, removes the newest."""
        with self._lock:
            stack = self._providers.get(name)
            if not stack:
                return
            if handle is None:
                stack.pop()
            else:
                self._providers[name] = [
                    (h, f) for h, f in stack if h is not handle
                ]
            if not self._providers[name]:
                del self._providers[name]

    # -- read side -----------------------------------------------------

    def _counters_and_gauges(self) -> tuple[dict, dict]:
        """Counters + provider-resolved gauges (shared by snapshot and
        prometheus_text so a scrape never reads the registry twice).
        Provider callbacks run OUTSIDE the lock."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            providers = {
                name: stack[-1][1]
                for name, stack in self._providers.items()
                if stack
            }
        for name, fn in providers.items():
            try:
                for suffix, value in (fn() or {}).items():
                    gauges[f"{name}.{suffix}"] = value
            except Exception:
                gauges[f"{name}.error"] = 1
        return counters, gauges

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            raws = {
                name: h.raw(now) for name, h in self._hists.items()
            }
            legacy = {k: dict(v) for k, v in self._samples.items()}
        counters, gauges = self._counters_and_gauges()
        samples = {
            name: _hist_stats(self._bounds, raw, now)
            for name, raw in raws.items()
        }
        for s in legacy.values():
            s["mean"] = s["sum"] / s["count"] if s["count"] else 0.0
        samples.update(legacy)
        return {
            "uptime_seconds": round(time.time() - _START, 3),
            "counters": counters,
            "gauges": gauges,
            "samples": samples,
        }

    def histogram_raw(self, name: str) -> Optional[dict]:
        """Bucket-level view of one histogram (bench/test introspection):
        {bounds, counts, count, sum, min, max, window}."""
        now = time.monotonic()
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                return None
            raw = h.raw(now)
        raw["bounds"] = list(self._bounds)
        return raw

    def prometheus_text(self) -> str:
        """The registry in Prometheus text exposition format (0.0.4) —
        what a stock Prometheus scrapes from /v1/metrics?format=prometheus
        (reference: command/agent/command.go:979-1036 wires a prometheus
        sink beside the inmem one).

        counters → <name>_total counter; gauges → gauge; histograms →
        real histogram exposition (_bucket{le=...} cumulative counts,
        _sum, _count) with min/max/last as companion gauges. Bucket
        lines are trimmed past the first bound covering the observed
        max (every higher bucket holds the same cumulative count) —
        +Inf always closes the series."""
        now = time.monotonic()
        with self._lock:
            raws = {n: h.raw(now) for n, h in self._hists.items()}
            legacy = {k: dict(v) for k, v in self._samples.items()}
        counters, gauges = self._counters_and_gauges()
        lines: list[str] = []

        def emit(name: str, kind: str, value: float) -> None:
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {_prom_value(value)}")

        emit(
            "nomad_uptime_seconds", "gauge",
            round(time.time() - _START, 3),
        )
        for name, v in sorted(counters.items()):
            emit(_prom_name(name) + "_total", "counter", v)
        for name, v in sorted(gauges.items()):
            emit(_prom_name(name), "gauge", v)
        for name, raw in sorted(raws.items()):
            n = _prom_name(name)
            lines.append(f"# TYPE {n} histogram")
            cum = 0
            vmax = raw["max"] if raw["count"] else 0.0
            for i, bound in enumerate(self._bounds):
                cum += raw["counts"][i]
                lines.append(
                    f'{n}_bucket{{le="{_prom_le(bound)}"}} {cum}'
                )
                if bound >= vmax:
                    break
            lines.append(f'{n}_bucket{{le="+Inf"}} {raw["count"]}')
            lines.append(f"{n}_sum {_prom_value(raw['sum'])}")
            lines.append(f"{n}_count {_prom_value(raw['count'])}")
            for stat in ("min", "max", "last"):
                v = raw[stat]
                if v in (inf, -inf):
                    v = 0.0
                emit(f"{n}_{stat}", "gauge", v)
        for name, s in sorted(legacy.items()):
            n = _prom_name(name)
            lines.append(f"# TYPE {n} summary")
            lines.append(f"{n}_sum {_prom_value(s['sum'])}")
            lines.append(f"{n}_count {_prom_value(s['count'])}")
            for stat in ("min", "max", "last"):
                emit(f"{n}_{stat}", "gauge", s[stat])
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Test helper: forget everything (providers included)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._samples.clear()
            self._providers.clear()
            self._timing_sinks.clear()


_global = Registry()


def registry() -> Registry:
    return _global


# Module-level conveniences: the hot paths call these directly (via
# `metrics.observe(...)` — an attribute lookup per call, which is what
# lets _install_registry swap the backing registry for tests/benches).
incr = _global.incr
set_gauge = _global.set_gauge
observe = _global.observe
time_ns = _global.time_ns
register_provider = _global.register_provider
unregister_provider = _global.unregister_provider
snapshot = _global.snapshot
prometheus_text = _global.prometheus_text


def _install_registry(reg: Registry) -> Registry:
    """Swap the process-global registry (returns the previous one).
    Test/bench hook: every call site reads `metrics.<fn>` through the
    module at call time, so rebinding here retargets them all — the
    histogram-vs-sample throughput comparator swaps a legacy-mode
    Registry in with this."""
    global _global, incr, set_gauge, observe, time_ns
    global register_provider, unregister_provider, snapshot, prometheus_text
    old = _global
    _global = reg
    incr = reg.incr
    set_gauge = reg.set_gauge
    observe = reg.observe
    time_ns = reg.time_ns
    register_provider = reg.register_provider
    unregister_provider = reg.unregister_provider
    snapshot = reg.snapshot
    prometheus_text = reg.prometheus_text
    return old


import re as _re


def _prom_name(name: str) -> str:
    out = _re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _prom_le(bound: float) -> str:
    """le label: shortest stable decimal (Prometheus compares le labels
    as strings across scrapes, so formatting must be deterministic)."""
    return f"{bound:.10g}"


class StatsdSink:
    """Push-mode telemetry: periodically emits the registry to a statsd
    daemon over UDP (reference: command/agent/command.go:1002 wires
    statsd_address into a go-metrics fanout sink).

    gauges ride as |g; counters as |c DELTAS since the last push (statsd
    counters are rate-counters, so a monotonic total must be
    differenced); histogram observations as |ms timings (milliseconds —
    drained from the registry's bounded raw-capture buffer, so the
    daemon aggregates real per-observation values, not re-bucketed
    approximations), with per-name count/sum gauges kept for dashboards
    that rate() them."""

    def __init__(self, address: str, interval_s: float = 10.0,
                 reg: Optional[Registry] = None) -> None:
        import socket

        host, sep, port = address.rpartition(":")
        if not sep or not port.isdigit():
            raise ValueError(
                f"statsd_address must be host:port, got {address!r}"
            )
        self.addr = (host.strip("[]") or "127.0.0.1", int(port))
        # a zero/negative interval would busy-loop the sink thread
        self.interval_s = max(1.0, float(interval_s))
        self.reg = reg or _global
        self._timing_handle = self.reg.enable_timing_capture()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._last_counters: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="statsd-sink"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # a stopped sink must not leave the registry capturing raw
        # observations nobody will ever drain
        self.reg.disable_timing_capture(self._timing_handle)
        if self._thread:
            self._thread.join(timeout=2)
        self._sock.close()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.push_once()
            except OSError:
                pass  # daemon away; keep trying

    def _decorate(self, line: str) -> str:
        """Per-line hook for dialect extensions (DogStatsD tags)."""
        return line

    def push_once(self) -> int:
        snap = self.reg.snapshot()
        lines: list[str] = []
        for name, v in snap["counters"].items():
            delta = v - self._last_counters.get(name, 0)
            self._last_counters[name] = v
            if delta:
                lines.append(self._decorate(
                    f"{_prom_name(name)}:{_prom_value(delta)}|c"))
        for name, v in snap["gauges"].items():
            lines.append(self._decorate(
                f"{_prom_name(name)}:{_prom_value(v)}|g"))
        for name, s in snap["samples"].items():
            n = _prom_name(name)
            lines.append(self._decorate(
                f"{n}.count:{_prom_value(s['count'])}|g"))
            lines.append(self._decorate(
                f"{n}.sum:{_prom_value(s['sum'])}|g"))
        # raw observations since the last push, as timings (seconds ->
        # milliseconds per the statsd convention)
        for name, values in self.reg.drain_timings(
            self._timing_handle
        ).items():
            n = _prom_name(name)
            for v in values:
                lines.append(self._decorate(f"{n}:{v * 1000:.3f}|ms"))
        sent = 0
        buf: list[str] = []
        size = 0
        for line in lines:
            if size + len(line) > 1400 and buf:  # stay under typical MTU
                self._sock.sendto("\n".join(buf).encode(), self.addr)
                sent += len(buf)
                buf, size = [], 0
            buf.append(line)
            size += len(line) + 1
        if buf:
            self._sock.sendto("\n".join(buf).encode(), self.addr)
            sent += len(buf)
        return sent


class DatadogSink(StatsdSink):
    """DogStatsD flavor of the statsd push (reference:
    command/agent/command.go:1010 wires datadog_address into a
    datadog.NewDogStatsdSink): same wire protocol plus |#tag:value
    annotations. Constant tags (node name, region, datacenter) ride on
    every metric, which is how the reference's DogStatsd sink attaches
    its host tags."""

    def __init__(self, address: str, interval_s: float = 10.0,
                 reg: Optional[Registry] = None,
                 tags: Optional[dict] = None) -> None:
        super().__init__(address, interval_s, reg)
        self._suffix = ""
        if tags:
            joined = ",".join(f"{k}:{v}" for k, v in sorted(tags.items()))
            self._suffix = f"|#{joined}"

    def _decorate(self, line: str) -> str:
        return line + self._suffix
