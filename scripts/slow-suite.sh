#!/usr/bin/env bash
# Pre-release slow battery: everything tier-1 skips, in one invocation.
#
#   scripts/slow-suite.sh            # the full slow-marked set
#   scripts/slow-suite.sh -k soak    # narrow with any extra pytest args
#
# Covers the slow-marked soak (10-minute sustained traffic with faults,
# tests/test_soak.py), the long chaos scenarios (fsync churn etc.,
# tests/test_chaos.py), the production-ops resilience acceptance
# batteries (tests/test_scenarios.py: 25-seed secret rotation, 25-seed
# rolling upgrade, long spot-node churn — narrow with `-m scenario`),
# the fleet-scale survival soak (>=5k simulated nodes held 10 minutes
# through a mass-expiry + mass-reconnect storm, tests/test_fleet.py —
# narrow with `-m fleet`), and the profiler/observability overhead
# batteries at full length — plus anything else that grows a `slow`
# mark. Runs on the CPU backend
# (the tier-1 posture); point JAX_PLATFORMS elsewhere to exercise a
# real device.
#
# After the pytest battery, runs the smoke_interactive bench config
# (interactive fast path: direct single-eval p50 vs the r08 basis +
# the loaded priority-lane ratio; skip with SLOW_SUITE_NO_INTERACTIVE=1)
# and the c2m_sharded bench sweep (100k+ nodes over mesh sizes 1 and 8
# through the production mesh path), failing if the sharded_scaling
# gate (>= 0.7x linear) or the zero-full-reupload/recompile-bound
# gates regress. Skip the sweep with SLOW_SUITE_NO_SHARDED=1 (e.g. on
# a box mid-perf-capture, where a concurrent sweep would skew
# BENCH_r0N numbers).
#
# Exit code: nonzero on any pytest failure or sharded-gate failure.
# Budget ~30+ minutes.
set -euo pipefail
cd "$(dirname "$0")/.."

: "${JAX_PLATFORMS:=cpu}"
export JAX_PLATFORMS

python -m pytest tests/ -q -m slow \
  --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly \
  "$@"

if [ "${SLOW_SUITE_NO_INTERACTIVE:-0}" != "1" ]; then
  echo "[slow-suite] interactive fast-path gates (BENCH_CONFIG=smoke_interactive)"
  python - <<'PY'
import json, os, subprocess, sys

env = dict(os.environ, BENCH_CONFIG="smoke_interactive")
env.setdefault("BENCH_SKIP_TPU_PROBE", "1")
proc = subprocess.run(
    [sys.executable, "bench.py"], env=env, capture_output=True, text=True
)
sys.stderr.write(proc.stderr[-2000:])
if proc.returncode != 0:
    sys.exit(f"smoke_interactive run failed rc={proc.returncode}")
payload = json.loads(proc.stdout.strip().splitlines()[-1])
cfg = payload["configs"]["smoke_interactive"]
print(
    "[slow-suite] smoke_interactive: direct p50 %.2fms (gate %s), "
    "loaded lane p50 %.1fms vs batch p50 %sms (gate %s)"
    % (
        cfg["single_eval_p50_s"] * 1e3,
        cfg["smoke_interactive_p50_ok"],
        cfg["lane_loaded_p50_s"] * 1e3,
        (cfg["batch_lane_p50_s"] or 0) * 1e3,
        cfg["smoke_interactive_lane_ok"],
    )
)
ok = cfg["smoke_interactive_p50_ok"] and cfg["smoke_interactive_lane_ok"]
sys.exit(0 if ok else "smoke_interactive gates failed")
PY
fi

if [ "${SLOW_SUITE_NO_SHARDED:-0}" != "1" ]; then
  echo "[slow-suite] c2m_sharded device-count sweep (BENCH_CONFIG=c2m_sharded)"
  BENCH_CONFIG=c2m_sharded python - <<'PY'
import json, os, subprocess, sys

env = dict(os.environ, BENCH_CONFIG="c2m_sharded")
proc = subprocess.run(
    [sys.executable, "bench.py"], env=env, capture_output=True, text=True
)
sys.stderr.write(proc.stderr[-2000:])
if proc.returncode != 0:
    sys.exit(f"c2m_sharded sweep failed rc={proc.returncode}")
cfg = json.loads(proc.stdout.strip().splitlines()[-1])["configs"]["c2m_sharded"]
# After the warmup sync ("full"), every steady-round resident sync must
# be a delta scatter or clean — a "full" mid-run means the resident
# shards re-uploaded (docs/sharding.md § re-upload vs delta-sync triage).
steady_fulls = sum(
    1
    for mesh in cfg["per_mesh"].values()
    for mode in mesh["resident_sync_modes"][1:]
    if mode.startswith("full")
)
recompiles = cfg["solver_observability"]["recompiles_after_warmup"]
print(
    "[slow-suite] sharded_scaling=%.3f (gate >= 0.7), "
    "steady_full_reuploads=%d, recompiles_after_warmup=%d"
    % (cfg["sharded_scaling"], steady_fulls, recompiles)
)
ok = (
    cfg["sharded_scaling"] >= cfg["sharded_scaling_linear_gate"]
    and steady_fulls == 0
    and recompiles == 0
)
sys.exit(0 if ok else "c2m_sharded gates failed")
PY
fi
