#!/usr/bin/env bash
# Pre-release slow battery: everything tier-1 skips, in one invocation.
#
#   scripts/slow-suite.sh            # the full slow-marked set
#   scripts/slow-suite.sh -k soak    # narrow with any extra pytest args
#
# Covers the slow-marked soak (10-minute sustained traffic with faults,
# tests/test_soak.py), the long chaos scenarios (fsync churn etc.,
# tests/test_chaos.py), the production-ops resilience acceptance
# batteries (tests/test_scenarios.py: 25-seed secret rotation, 25-seed
# rolling upgrade, long spot-node churn — narrow with `-m scenario`),
# and the profiler/observability overhead batteries at full length —
# plus anything else that grows a `slow` mark. Runs on the CPU backend
# (the tier-1 posture); point JAX_PLATFORMS elsewhere to exercise a
# real device.
#
# After the pytest battery, runs the c2m_sharded bench sweep (100k+
# nodes over mesh sizes 1 and 8 through the production mesh path) and
# fails if its sharded_scaling gate (>= 0.7x linear) or the
# zero-full-reupload/recompile-bound gates regress. Skip it with
# SLOW_SUITE_NO_SHARDED=1 (e.g. on a box mid-perf-capture, where a
# concurrent sweep would skew BENCH_r0N numbers).
#
# Exit code: nonzero on any pytest failure or sharded-gate failure.
# Budget ~30+ minutes.
set -euo pipefail
cd "$(dirname "$0")/.."

: "${JAX_PLATFORMS:=cpu}"
export JAX_PLATFORMS

python -m pytest tests/ -q -m slow \
  --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly \
  "$@"

if [ "${SLOW_SUITE_NO_SHARDED:-0}" != "1" ]; then
  echo "[slow-suite] c2m_sharded device-count sweep (BENCH_CONFIG=c2m_sharded)"
  BENCH_CONFIG=c2m_sharded python - <<'PY'
import json, os, subprocess, sys

env = dict(os.environ, BENCH_CONFIG="c2m_sharded")
proc = subprocess.run(
    [sys.executable, "bench.py"], env=env, capture_output=True, text=True
)
sys.stderr.write(proc.stderr[-2000:])
if proc.returncode != 0:
    sys.exit(f"c2m_sharded sweep failed rc={proc.returncode}")
cfg = json.loads(proc.stdout.strip().splitlines()[-1])["configs"]["c2m_sharded"]
# After the warmup sync ("full"), every steady-round resident sync must
# be a delta scatter or clean — a "full" mid-run means the resident
# shards re-uploaded (docs/sharding.md § re-upload vs delta-sync triage).
steady_fulls = sum(
    1
    for mesh in cfg["per_mesh"].values()
    for mode in mesh["resident_sync_modes"][1:]
    if mode.startswith("full")
)
recompiles = cfg["solver_observability"]["recompiles_after_warmup"]
print(
    "[slow-suite] sharded_scaling=%.3f (gate >= 0.7), "
    "steady_full_reuploads=%d, recompiles_after_warmup=%d"
    % (cfg["sharded_scaling"], steady_fulls, recompiles)
)
ok = (
    cfg["sharded_scaling"] >= cfg["sharded_scaling_linear_gate"]
    and steady_fulls == 0
    and recompiles == 0
)
sys.exit(0 if ok else "c2m_sharded gates failed")
PY
fi
