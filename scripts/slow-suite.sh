#!/usr/bin/env bash
# Pre-release slow battery: everything tier-1 skips, in one invocation.
#
#   scripts/slow-suite.sh            # the full slow-marked set
#   scripts/slow-suite.sh -k soak    # narrow with any extra pytest args
#
# Covers the slow-marked soak (10-minute sustained traffic with faults,
# tests/test_soak.py), the long chaos scenarios (fsync churn etc.,
# tests/test_chaos.py), the production-ops resilience acceptance
# batteries (tests/test_scenarios.py: 25-seed secret rotation, 25-seed
# rolling upgrade, long spot-node churn — narrow with `-m scenario`),
# and the profiler/observability overhead batteries at full length —
# plus anything else that grows a `slow` mark. Runs on the CPU backend
# (the tier-1 posture); point JAX_PLATFORMS elsewhere to exercise a
# real device.
#
# Exit code is pytest's: nonzero on any failure. Budget ~30+ minutes.
set -euo pipefail
cd "$(dirname "$0")/.."

: "${JAX_PLATFORMS:=cpu}"
export JAX_PLATFORMS

exec python -m pytest tests/ -q -m slow \
  --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly \
  "$@"
