#!/usr/bin/env python3
"""Fastpack compile smoke: build the C extension FRESH (cold cache),
import it, and run identity spot-checks against the pure-Python
fallbacks. tests/test_native.py runs this as part of tier-1 so a
broken C toolchain fails loudly instead of silently demoting every
hot path (pack, bulk ids, wire rows, port picking, store inserts) to
the fallbacks.

Usage: python scripts/fastpack_smoke.py
Honors NOMAD_TPU_BIN_DIR; defaults to a fresh temp dir so the gcc
compile actually runs rather than reusing the user cache.
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    os.environ.pop("NOMAD_TPU_NO_FASTPACK", None)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    tmp = None
    if not os.environ.get("NOMAD_TPU_BIN_DIR"):
        tmp = tempfile.TemporaryDirectory(prefix="fastpack-smoke-")
        os.environ["NOMAD_TPU_BIN_DIR"] = tmp.name

    from nomad_tpu import codec, native

    if not codec.warm_native():
        print("FAIL: fastpack did not build (see nomad_tpu.native log)")
        return 1
    fp = codec.native_module()
    missing = [
        n for n in native.FASTPACK_ENTRY_POINTS
        if not callable(getattr(fp, n, None))
    ]
    if missing:
        print(f"FAIL: missing entry points: {missing}")
        return 1

    # identity spot-checks vs the pure-Python fallbacks
    from nomad_tpu.structs.structs import _uuid_hex_py

    raw = bytes(range(16)) * 4
    if fp.uuid_hex(raw) != _uuid_hex_py(raw):
        print("FAIL: uuid_hex parity")
        return 1

    import numpy as np

    from nomad_tpu.state.store import StateStore

    idx = np.array([3, 0, 3, 1, 0, 2, 2, 3], dtype=np.int32)
    ids = [f"id-{i}" for i in range(len(idx))]
    hs = list(range(len(idx)))
    c_tabs = ({}, {}, {}, {t: {} for t in range(4)})
    fp.store_rows(ids, hs, idx.tobytes(), *c_tabs)
    py_tabs = ({}, {}, {}, {t: {} for t in range(4)})
    StateStore._store_rows_py(ids, hs, idx.tolist(), *py_tabs)
    if c_tabs != py_tabs or list(c_tabs[0]) != list(py_tabs[0]):
        print("FAIL: store_rows parity")
        return 1

    print(
        f"fastpack smoke OK: resolved in {native.last_build_seconds:.2f}s; "
        f"{len(native.FASTPACK_ENTRY_POINTS)} entry points live"
    )
    if tmp is not None:
        tmp.cleanup()
    return 0


if __name__ == "__main__":
    sys.exit(main())
