#!/usr/bin/env bash
# nomad-vet, one command (docs/static-analysis.md):
#
#   scripts/vet.sh              # static walk + dynamic racecheck battery
#   scripts/vet.sh -static      # the <10s static walk only
#
# 1. `operator vet` — the AST analyzer over the production tree,
#    gating on zero unsuppressed findings (analysis/baseline.toml is
#    the reviewed exception ledger).
# 2. The dynamic lock-order battery (tests/test_racecheck.py runs the
#    full-stack exercises in clean subprocesses under NOMAD_RACECHECK)
#    plus tests/test_analysis.py — fixtures per rule, the baseline
#    round-trip, and the static/dynamic edge cross-check.
#
# CI runs both via tier-1; this script is the pre-push shortcut.
# Exit is nonzero on any finding or test failure.
set -euo pipefail
cd "$(dirname "$0")/.."

: "${JAX_PLATFORMS:=cpu}"
export JAX_PLATFORMS

python -m nomad_tpu.cli operator vet

if [[ "${1:-}" == "-static" ]]; then
  exit 0
fi

exec python -m pytest tests/test_analysis.py tests/test_racecheck.py \
  -q -m 'not slow' \
  -p no:cacheprovider -p no:xdist -p no:randomly \
  "${@}"
