"""C2M-style scheduler benchmark (BASELINE.md configs).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "evals/sec", "vs_baseline": N}

vs_baseline = TPU-batch evals/sec ÷ host-oracle evals/sec on the same
cluster/job shapes. The host oracle is this repo's faithful reimplementation
of the reference's per-eval iterator scheduler (scheduler/generic_sched.go)
— the Go binary itself is not runnable here, so the oracle stands in as the
baseline denominator; BASELINE.md's target is ≥20x at ≤1% worse packing
density (density is asserted and reported on stderr).

Configs (BENCH_CONFIG env):
  smoke   — 10 nodes, 1 job (TestServiceSched_JobRegister analog)
  c1k     — 1k nodes / 5k allocs, cpu+mem only (pure ScoreFit)
  c2m     — 10k nodes / 100k allocs with constraint+spread load  [default]
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def build_cluster(n_nodes: int, n_jobs: int, count: int, constrained: bool):
    from nomad_tpu import mock
    from nomad_tpu.structs import Constraint, Spread
    from nomad_tpu.structs.node_class import compute_node_class
    from nomad_tpu.testing import Harness

    h = Harness()
    dcs = ["dc1", "dc2", "dc3", "dc4"]
    for i in range(n_nodes):
        n = mock.node()
        n.datacenter = dcs[i % len(dcs)]
        # 16 instances of the bench task per node (cpu-bound)
        n.resources.cpu = 4000
        n.resources.memory_mb = 8192
        n.computed_class = compute_node_class(n)
        h.state.upsert_node(h.next_index(), n)
    jobs = []
    for j in range(n_jobs):
        job = mock.job(id=f"bench-{j}")
        job.datacenters = dcs
        tg = job.task_groups[0]
        tg.count = count
        tg.tasks[0].resources.cpu = 250
        tg.tasks[0].resources.memory_mb = 128
        tg.tasks[0].resources.networks = []
        if constrained:
            job.constraints.append(
                Constraint("${attr.kernel.name}", "linux", "=")
            )
            job.spreads = [Spread(attribute="${node.datacenter}", weight=50)]
        h.state.upsert_job(h.next_index(), job)
        jobs.append(job)
    return h, jobs


def density(h, jobs) -> tuple[int, int]:
    """(total placed, nodes touched)."""
    nodes = set()
    placed = 0
    for job in jobs:
        for a in h.state.allocs_by_job(job.namespace, job.id):
            if not a.terminal_status():
                placed += 1
                nodes.add(a.node_id)
    return placed, len(nodes)


def run_host(n_nodes, n_jobs, count, constrained, sample):
    from nomad_tpu import mock

    h, jobs = build_cluster(n_nodes, n_jobs, count, constrained)
    sample_jobs = jobs[:sample]
    t0 = time.perf_counter()
    for job in sample_jobs:
        h.process(job.type, mock.eval_for_job(job))
    dt = time.perf_counter() - t0
    placed, nodes_used = density(h, sample_jobs)
    return len(sample_jobs) / dt, placed, nodes_used, dt


def run_tpu(n_nodes, n_jobs, count, constrained):
    from nomad_tpu import mock
    from nomad_tpu.scheduler.tpu import solve_eval_batch

    h, jobs = build_cluster(n_nodes, n_jobs, count, constrained)
    snap = h.snapshot()

    # Warm the jit cache at the exact padded shapes of the measured run —
    # steady-state scheduling is the metric; compiles amortize across the
    # server's lifetime.
    warm_evals = [mock.eval_for_job(job) for job in jobs]
    solve_eval_batch(snap, h, warm_evals)

    evals = [mock.eval_for_job(job) for job in jobs]
    t0 = time.perf_counter()
    plans = solve_eval_batch(snap, h, evals)
    for ev in evals:
        h.submit_plan(plans[ev.id])
    dt = time.perf_counter() - t0
    placed, nodes_used = density(h, jobs)
    return len(evals) / dt, placed, nodes_used, dt


CONFIGS = {
    # name: (nodes, jobs, count/job, constrained, host_sample)
    "smoke": (10, 1, 10, False, 1),
    "c1k": (1000, 50, 100, False, 10),
    "c2m": (10000, 100, 1000, True, 5),
}


def main():
    name = os.environ.get("BENCH_CONFIG", "c2m")
    n_nodes, n_jobs, count, constrained, host_sample = CONFIGS[name]
    log(f"bench config={name}: {n_nodes} nodes, {n_jobs} jobs x {count} allocs")

    tpu_rate, tpu_placed, tpu_nodes, tpu_dt = run_tpu(
        n_nodes, n_jobs, count, constrained
    )
    log(
        f"tpu:  {tpu_rate:.2f} evals/s ({tpu_dt:.2f}s), placed {tpu_placed}, "
        f"nodes used {tpu_nodes}"
    )

    host_rate, host_placed, host_nodes, host_dt = run_host(
        n_nodes, n_jobs, count, constrained, host_sample
    )
    log(
        f"host: {host_rate:.2f} evals/s ({host_dt:.2f}s over {host_sample} evals), "
        f"placed {host_placed}, nodes used {host_nodes}"
    )

    # Packing-density parity: allocs per touched node, normalized.
    tpu_density = tpu_placed / max(1, tpu_nodes)
    host_density = host_placed / max(1, host_nodes)
    log(
        f"density: tpu {tpu_density:.2f} allocs/node vs host {host_density:.2f} "
        f"(ratio {tpu_density / max(host_density, 1e-9):.3f})"
    )

    print(
        json.dumps(
            {
                "metric": f"{name}_scheduler_throughput",
                "value": round(tpu_rate, 2),
                "unit": "evals/sec",
                "vs_baseline": round(tpu_rate / host_rate, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
