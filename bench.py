"""C2M-style scheduler benchmark — all five BASELINE.md configs.

Prints ONE JSON line whose headline is the c2m config:
  {"metric": "c2m_scheduler_throughput", "value": N, "unit": "evals/sec",
   "vs_baseline": N, "configs": {...per-config results...}, "caveats": [...]}

vs_baseline = TPU-batch evals/sec ÷ host-oracle evals/sec on the same
cluster/job shapes. The host oracle is this repo's faithful reimplementation
of the reference's per-eval iterator scheduler (scheduler/generic_sched.go).
The Go binary itself is not runnable here, so the oracle stands in as the
baseline denominator — see the "caveats" field: Go is typically much faster
than equivalent Python, so these ratios overstate the margin vs the actual
reference. Density parity (the ≤1% BASELINE criterion) is measured at EQUAL
placed load: the host sample's jobs are re-solved by the TPU backend on an
identical fresh cluster and allocs-per-touched-node is compared directly.

Configs (BASELINE.md "configs"; BENCH_CONFIG env selects one, default all):
  smoke   — 10 nodes, 1 job (TestServiceSched_JobRegister analog)
  c1k     — 1k nodes / 5k allocs, cpu+mem only (pure ScoreFit)
  c2m     — 10k nodes / 100k allocs with constraint+spread load
  preempt — 90%-full cluster, high-priority wave preempting a low tier
  drain   — service+system placed, then 10% of nodes drain (re-solve churn)
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time
from statistics import median

import numpy as np


def log(*args):
    print(*args, file=sys.stderr, flush=True)


# flipped when a native baseline was actually measured; gates its caveat
_NATIVE_CAVEAT = [False]

NATIVE_CAVEAT_TEXT = (
    "vs_native_cpp divides the TPU-batch rate by a measured C++ "
    "reimplementation of the scheduler's placement hot loop "
    "(bench_native/sched_bench.cc) on this machine — the Go toolchain "
    "is absent here so the reference binary cannot be built; the C++ "
    "loop excludes reconcile/plan-apply/state costs, so it OVERSTATES "
    "the native side and vs_native_cpp is a conservative lower bound"
)

CAVEATS = [
    "host oracle is this repo's Python reimplementation of the reference "
    "GenericScheduler; the Go reference is typically 30-100x faster than "
    "equivalent Python, so vs_baseline overstates the margin vs Go by "
    "roughly that factor",
    "smoke measures single-eval latency, where the TPU device round-trip "
    "(~0.15s here, through a tunnel) dominates; the TPU backend is a "
    "batch-throughput design",
    "drain config: service evals run the batched solver; the system eval "
    "runs the TPU backend's vectorized system scheduler (one lowered "
    "feasibility+capacity pass, per-node fallback for ports/devices)",
    "when tpu_available=false the TPU device was unreachable at bench "
    "time and every number was measured on CPU fallback — the TPU "
    "solve itself is strictly faster than what is recorded here",
]


def build_cluster(n_nodes: int, n_jobs: int, count: int, constrained: bool,
                  priority: int = 50, job_prefix: str = "bench",
                  cpu: int = 250, mem: int = 128):
    from nomad_tpu import mock
    from nomad_tpu.gctune import paused_gc
    from nomad_tpu.structs import Constraint, Spread
    from nomad_tpu.structs.node_class import compute_node_class
    from nomad_tpu.testing import Harness

    # One bounded allocation burst (10k nodes + the job set), frozen on
    # exit: the built cluster IS resident heap, so it goes straight to
    # the permanent generation instead of being young-gen-scanned (with
    # every gc callback, jax's included) at the first post-build
    # collection (gctune.paused_gc).
    with paused_gc(freeze_on_exit=True):
        h = Harness()
        dcs = ["dc1", "dc2", "dc3", "dc4"]
        for i in range(n_nodes):
            n = mock.node()
            n.datacenter = dcs[i % len(dcs)]
            n.resources.cpu = 4000
            n.resources.memory_mb = 8192
            n.computed_class = compute_node_class(n)
            h.state.upsert_node(h.next_index(), n)
        jobs = add_jobs(h, n_jobs, count, constrained, priority, job_prefix,
                        cpu, mem)
    return h, jobs


def add_jobs(h, n_jobs, count, constrained, priority=50, job_prefix="bench",
             cpu=250, mem=128):
    from nomad_tpu import mock
    from nomad_tpu.structs import Constraint, Spread

    dcs = ["dc1", "dc2", "dc3", "dc4"]
    jobs = []
    for j in range(n_jobs):
        job = mock.job(id=f"{job_prefix}-{j}")
        job.datacenters = dcs
        job.priority = priority
        tg = job.task_groups[0]
        tg.count = count
        tg.tasks[0].resources.cpu = cpu
        tg.tasks[0].resources.memory_mb = mem
        tg.tasks[0].resources.networks = []
        if constrained:
            job.constraints.append(
                Constraint("${attr.kernel.name}", "linux", "=")
            )
            job.spreads = [Spread(attribute="${node.datacenter}", weight=50)]
        h.state.upsert_job(h.next_index(), job)
        jobs.append(job)
    return jobs


def density(h, jobs) -> tuple[int, int]:
    """(total live placed, nodes touched)."""
    nodes = set()
    placed = 0
    for job in jobs:
        for a in h.state.allocs_by_job(job.namespace, job.id):
            if not a.terminal_status():
                placed += 1
                nodes.add(a.node_id)
    return placed, len(nodes)


def tpu_place(h, jobs, config=None, warm=True, resident=None):
    """Solve + submit all jobs' evals in one batch; returns (dt, plans).

    With BENCH_TRACE=1 every measured batch runs under a trace context
    (nomad_tpu/trace.py), so the BENCH breakdown comes from the SAME
    span machinery production serves at /v1/traces — not a parallel set
    of hand-wired timers. The trace rides the global recorder; the
    configs' summaries are published under each result's "trace" key."""
    from nomad_tpu import codec, mock, trace
    from nomad_tpu.scheduler.tpu import solve_eval_batch

    # the bulk id-minting/plan-row fast paths ride the fastpack
    # extension; resolve it here, outside any lock (codec.warm_native)
    codec.warm_native()

    from nomad_tpu.gctune import paused_gc

    snap = h.snapshot()
    if warm:
        # Warm the jit cache at the exact padded shapes of the measured
        # run — steady-state scheduling is the metric; compiles amortize
        # across the server's lifetime.
        solve_eval_batch(
            snap, h, [mock.eval_for_job(j) for j in jobs], config,
            resident=resident,
        )
    evals = [mock.eval_for_job(job) for job in jobs]
    ctx = trace.start_trace("bench.batch", evals=len(evals))
    t0 = time.perf_counter()
    # the whole solve->commit pipeline is one paused-GC section (the
    # inner solver/store sections nest): the gaps between per-eval plan
    # submissions were paying young-gen scans + the jax gc callback.
    # freeze_on_exit: the survivors are committed store rows — resident
    # heap by definition — so they skip the deferred scan entirely
    with trace.use(ctx), paused_gc(freeze_on_exit=True):
        plans = solve_eval_batch(snap, h, evals, config, resident=resident)
        with trace.span(ctx, "plan.submit"):
            for ev in evals:
                h.submit_plan(plans[ev.id])
    dt = time.perf_counter() - t0
    if ctx is not None:
        ctx.finish()
    return dt, plans


def trace_summary() -> dict | None:
    """Critical-path summary of the bench.batch traces recorded so far
    (BENCH_TRACE=1): top span names by total self-time, from the same
    machinery /v1/traces and `operator trace -summary` read. Drains the
    recorder so each config reports only its own batches."""
    from nomad_tpu import trace

    if not trace.enabled():
        return None
    rec = trace.recorder()
    summaries = rec.list(name="bench.batch", limit=100)
    traces = [rec.get(s["id"]) for s in summaries]
    traces = [t for t in traces if t is not None]
    if not traces:
        return None
    top = trace.critical_path(traces, top=8)
    out = {
        "batches": len(traces),
        "top_self_time_ms": {
            name: round(ns / 1e6, 3) for name, ns in top
        },
        "last_trace_id": summaries[0]["id"],
        "last_coverage": round(trace.coverage(traces[0]), 4),
    }
    rec.clear()
    return out


def spread_pct(vals) -> float:
    """(max-min)/median — the run-to-run noise indicator VERDICT r4
    weak #4 asked for (this box has one core; absolute numbers swing
    with load, so every reported rate carries its spread)."""
    m = median(vals)
    return round((max(vals) - min(vals)) / m * 100, 1) if m else 0.0


def latency_percentiles() -> dict:
    """Per-stage percentile breakdown from the histogram machinery
    (metrics.py) — the SAME bucket counts production serves at
    /v1/metrics and `operator top` renders, published into the BENCH
    json so the capture of record carries distributions, not just
    medians-of-rates (VERDICT r5 weak #1: single-number captures hid a
    96.6% spread). Cumulative over the config's run (main() resets the
    registry between configs)."""
    from nomad_tpu import metrics

    out = {}
    for name, s in sorted(metrics.snapshot()["samples"].items()):
        if "p50" not in s or not s.get("count"):
            continue
        out[name] = {
            "count": int(s["count"]),
            "mean": round(s["mean"], 5),
            "p50": round(s["p50"], 5),
            "p90": round(s["p90"], 5),
            "p95": round(s["p95"], 5),
            "p99": round(s["p99"], 5),
            "max": round(s["max"], 5),
        }
    return out


def solver_breakdown() -> dict:
    """Last solve's host/device/transfer split from the telemetry
    registry (solver._run_compact records each phase): what fraction of
    a solve was host-side prep+dispatch, device compute, and readback
    over the link — the device/transfer/host breakdown of VERDICT r4
    item 2."""
    from nomad_tpu import metrics

    s = metrics.snapshot()["samples"]
    out = {}
    for key, name in (
        ("nomad.tpu.host_prep_seconds", "host_prep_s"),
        ("nomad.tpu.device_seconds", "device_s"),
        ("nomad.tpu.readback_seconds", "readback_s"),
        ("nomad.tpu.materialize_seconds", "materialize_s"),
        ("nomad.tpu.commit_seconds", "commit_s"),
    ):
        v = s.get(key)
        if v is not None:
            out[name] = round(v["last"], 4)
    return out


def host_attribution_pass(n_nodes, n_jobs, count, constrained,
                          wall_target_s: float = 2.0,
                          max_passes: int = 40) -> dict:
    """Per-config host_attribution block from the always-on profiler
    (nomad_tpu/hostobs.py) — the SAME machinery production serves at
    /v1/profile/status and `operator profile status` renders.

    Dedicated un-measured passes (they follow the measured trials and
    never touch the reported rates): the profiler records for the WHOLE
    phase — cluster builds included, under span "-"; solve/submit work
    under the bench.batch/plan.submit spans — because a statistical
    sampler charges each sample with the full gap since its previous
    wakeup, and gating recording around sub-windows silently drops
    every gap that straddles a boundary (measured ~50% attribution
    loss). Tracing is enabled so every sample carries its active span;
    passes repeat on fresh clusters until >= wall_target_s of SOLVE
    wall has accumulated (sampling density for the 15% span-agreement
    check).

    Publishes:
      host_fraction     attributed busy seconds / phase wall (all on
                        the host here; on a real device the block-wait
                        site is named in top_sites rather than excluded)
      coverage          fraction of phase wall covered by NAMED (span x
                        function) sites — the >= 0.8 c2m gate: ledger
                        overflow into "(other)", sampler starvation, or
                        idle-misclassified work shows up as lost
                        coverage
      gc_share          GC pause seconds / phase wall
      top_sites         top-10 self-time sites with pct-of-wall (span
                        "-" = outside any trace, e.g. cluster build)
      span_agreement    profiler per-span busy seconds vs the traces'
                        stack-self-times over the SAME passes
                        (trace.stack_self_times: pre-timed stage spans
                        excluded — profiling.md § Span semantics), with
                        agreement_ok on every span carrying >= 20% of
                        total traced self-time and >= 0.3s absolute
    """
    from nomad_tpu import hostobs, trace as _trace
    from nomad_tpu.scheduler.tpu import ResidentClusterState

    if not hostobs.running():
        hostobs.start()
    was_traced = _trace.enabled()
    _trace.set_enabled(True)
    rec = _trace.recorder()
    rec.clear()
    prof = hostobs.profiler()
    prev_intervals = (prof.interval_s, prof.idle_interval_s)
    # dense sampling for the attribution window (2ms, idle backoff
    # pinned): the spans being checked to 15% need the sample count,
    # and a burst following a long idle build must not start at the
    # backed-off rate. Restored to the production cadence after.
    hostobs.configure(interval_s=0.002, idle_interval_s=0.002)
    # One collect + resident freeze BEFORE reset_stats and the phase
    # timer: a per-pass collect would dominate the attribution window
    # with self-inflicted gen2 scans, and the freeze
    # (gctune.freeze_resident_heap — the post-warmup mitigation
    # production runs) must not appear as a measured site or pause.
    # Per-pass cluster builds freeze their own survivors on section
    # exit (build_cluster), so the phase measures gc_share with the
    # full mitigation active.
    from nomad_tpu.gctune import freeze_resident_heap

    freeze_resident_heap()
    hostobs.reset_stats()
    solve_wall = 0.0
    passes = 0
    t_phase = time.perf_counter()
    try:
        h = jobs = None
        while solve_wall < wall_target_s and passes < max_passes:
            h = jobs = None  # refcount-drop the previous cluster
            h, jobs = build_cluster(n_nodes, n_jobs, count, constrained)
            resident = ResidentClusterState()
            dt, _ = tpu_place(h, jobs, warm=False, resident=resident)
            solve_wall += dt
            passes += 1
        wall = time.perf_counter() - t_phase
        snap = hostobs.snapshot(top=50)
        trace_self_ns: dict[str, int] = {}
        for s in rec.list(name="bench.batch", limit=max_passes):
            t = rec.get(s["id"])
            if t is None:
                continue
            for span, ns in _trace.stack_self_times(t).items():
                trace_self_ns[span] = trace_self_ns.get(span, 0) + ns
    finally:
        hostobs.configure(
            interval_s=prev_intervals[0], idle_interval_s=prev_intervals[1]
        )
        _trace.set_enabled(was_traced)
        rec.clear()
    wall = max(wall, 1e-9)
    busy = snap["busy_seconds"]
    other_s = sum(
        s["seconds"] for s in snap["top_sites"] if s["site"] == "(other)"
    )
    named_busy = max(0.0, busy - other_s)
    prof_spans = snap["spans"]
    trace_total_s = sum(trace_self_ns.values()) / 1e9
    agreement = {}
    agreement_ok = True
    for span, ns in sorted(trace_self_ns.items(), key=lambda kv: -kv[1]):
        trace_s = ns / 1e9
        if trace_s < 0.05 * trace_total_s:
            continue  # too small for sampling statistics to judge
        prof_s = prof_spans.get(span, 0.0)
        ratio = prof_s / max(trace_s, 1e-9)
        entry = {
            "trace_s": round(trace_s, 4),
            "profiler_s": round(prof_s, 4),
            "ratio": round(ratio, 3),
        }
        if trace_s >= max(0.3, 0.2 * trace_total_s):
            entry["gated"] = True
            if not (0.85 <= ratio <= 1.15):
                agreement_ok = False
        agreement[span] = entry
    out = {
        "passes": passes,
        "wall_s": round(wall, 3),
        "solve_wall_s": round(solve_wall, 3),
        "samples": snap["samples"],
        "host_fraction": round(min(busy / wall, 1.0), 4),
        "coverage": round(min(named_busy / wall, 1.0), 4),
        "gc_share": round(
            snap["gc"]["pause_seconds_total"] / wall, 5
        ),
        "gc_collections": snap["gc"]["collections"],
        "lock_waits": snap["locks"],
        "top_sites": [
            {
                "span": s["span"],
                "site": s["site"],
                "seconds": s["seconds"],
                "pct_of_wall": round(s["seconds"] / wall * 100, 2),
            }
            for s in snap["top_sites"]
            if s["site"] != "(other)"
        ][:10],
        "span_agreement": agreement,
        "span_agreement_ok": agreement_ok,
        "profiler_overhead_duty_cycle": snap["overhead"]["duty_cycle"],
    }
    log(
        f"[host_attribution] {passes} pass(es) / {wall:.1f}s wall: "
        f"host_fraction {out['host_fraction']}, coverage "
        f"{out['coverage']}, gc_share {out['gc_share']}, agreement_ok "
        f"{agreement_ok} ({ {k: v['ratio'] for k, v in agreement.items()} })"
    )
    return out


def host_place(h, jobs, config=None, scheduler="service"):
    from nomad_tpu import mock

    t0 = time.perf_counter()
    for job in jobs:
        h.process(scheduler, mock.eval_for_job(job), config)
    return time.perf_counter() - t0


def solver_observability(compiles_at_warmup=None) -> dict:
    """Per-config solver_observability block from the observatory
    (nomad_tpu/solverobs.py) — the SAME snapshot production serves at
    /v1/solver/status and `operator solver status` renders: compile
    counts, steady-state recompiles, mean occupancy, transfer bytes.
    With compiles_at_warmup, also reports recompiles_after_warmup — the
    gates.recompile_bound input (the shape-bucketing contract in
    kernels.py says steady-state batches compile NOTHING)."""
    from nomad_tpu import solverobs

    snap = solverobs.snapshot(sample=False)
    occ = snap["occupancy"]
    out = {
        "compiles": snap["ledger"]["compiles"],
        "cache_hits": snap["ledger"]["cache_hits"],
        "steady_recompiles": snap["ledger"]["steady_recompiles"],
        "mean_occupancy": occ["mean"],
        "last_occupancy": (occ["last_batch"] or {}).get("occupancy"),
        "h2d_bytes": snap["transfers"]["h2d_bytes"],
        "d2h_bytes": snap["transfers"]["d2h_bytes"],
        "allgather_bytes": snap["transfers"]["allgather_bytes"],
        "scatter_bytes": snap["transfers"]["scatter_bytes"],
        "sharding": snap["sharding"],
        "device_memory": snap["device_memory"],
        "live_array_highwater_bytes": snap["live_array_highwater_bytes"],
    }
    if compiles_at_warmup is not None:
        out["recompiles_after_warmup"] = (
            out["compiles"] - compiles_at_warmup
        )
    return out


def solver_internal_seconds():
    """Last kernel-side solve time from the telemetry registry — the
    solver records nomad.tpu.solve_seconds on every batch (VERDICT r2:
    solver timings were measured then dropped)."""
    from nomad_tpu import metrics

    s = metrics.snapshot()["samples"].get("nomad.tpu.solve_seconds")
    return round(s["last"], 4) if s else None


def run_service_config(name, n_nodes, n_jobs, count, constrained, host_sample,
                       min_trial_s: float = 0.0, trials: int = 3):
    from nomad_tpu.scheduler.tpu import ResidentClusterState

    log(f"[{name}] {n_nodes} nodes, {n_jobs} jobs x {count} allocs")
    # full-load TPU throughput: median of fresh-cluster trials (this box
    # has one core; single-run captures swung 30%+ across rounds). With
    # min_trial_s (c2m: 20s, VERDICT r7 next-round #3) each trial
    # repeats the measured pass on fresh clusters until it holds that
    # much work, so one load spike can't be a whole sample.
    from nomad_tpu import solverobs

    rates, solve_ss = [], []
    resident_syncs = []
    h = jobs = None
    rounds = 1
    from nomad_tpu.gctune import freeze_resident_heap

    if min_trial_s > 0:
        gc.collect()
        h, jobs = build_cluster(n_nodes, n_jobs, count, constrained)
        # post-warmup freeze: the first cluster's heap (and everything
        # resident beneath it — jax, the store machinery) leaves the
        # collector's sight, so measured-pass collections walk only
        # young objects (ISSUE gc tax; gctune.freeze_resident_heap)
        freeze_resident_heap()
        warm_dt, _ = tpu_place(h, jobs, resident=ResidentClusterState())
        rounds = max(1, int(-(-min_trial_s // max(warm_dt, 1e-9))))
        log(
            f"[{name}] sizing pass {warm_dt:.1f}s -> {rounds} pass(es)/"
            f"trial (>= {min_trial_s:.0f}s of work), {trials} trials"
        )
    else:
        # un-measured warmup at the measured passes' exact padded
        # shapes, so the recompile-bound gate below sees steady state
        # only (the sizing pass plays this role when min_trial_s > 0);
        # warm=False: one solve populates the ledger, no double pass
        gc.collect()
        h, jobs = build_cluster(n_nodes, n_jobs, count, constrained)
        freeze_resident_heap()
        tpu_place(h, jobs, warm=False, resident=ResidentClusterState())
    # everything compiled from here on is a steady-state recompile
    compiles_at_warmup = solverobs.compiles()
    # control bursts bracket every trial (trials+1 bursts total): trial
    # i pairs with the mean of bursts i and i+1, temporally adjacent on
    # both sides, so a co-tenant load spike slows the trial AND its
    # controls together and the normalization cancels it
    control_burst()  # untimed warmup: the first in-process burst reads
    # ~30% cold (branch/cache ramp) and would bias trial 1's pairing
    ctrl_bursts = [control_burst()]
    from nomad_tpu.gctune import release_frozen_garbage

    pass_no = 0
    for trial in range(trials):
        dt_total = 0.0
        for _ in range(rounds):
            # drop the previous pass's cluster BEFORE building the next:
            # two live c2m heaps tank the later trials (memory pressure +
            # giant old-gen scans when the paused GC re-enables)
            h = jobs = None
            pass_no += 1
            if pass_no % 8 == 0:
                # each dropped frozen cluster strands its cycles in the
                # permanent generation (~64MB/pass at c2m scale); an
                # unfreeze+collect in the untimed gap bounds RSS
                release_frozen_garbage()
            else:
                gc.collect()
            h, jobs = build_cluster(n_nodes, n_jobs, count, constrained)
            resident = ResidentClusterState()
            tpu_dt, _ = tpu_place(h, jobs, resident=resident)
            dt_total += tpu_dt
            resident_syncs.append(resident.last_sync)
        rates.append(rounds * len(jobs) / dt_total)
        ctrl_bursts.append(control_burst())
        solve_ss.append(solver_internal_seconds() or 0.0)
    tpu_rate = median(rates)
    ctrl_per_trial = [
        (ctrl_bursts[i] + ctrl_bursts[i + 1]) / 2 for i in range(trials)
    ]
    norm_rates = [
        r * CONTROL_REF_OPS_S / max(c, 1e-9)
        for r, c in zip(rates, ctrl_per_trial)
    ]
    # median of PER-TRIAL normalized rates (median-of-ratios), not the
    # normalized median: each ratio pairs a trial with ITS adjacent
    # controls, which is what makes the statistic drift-immune
    tpu_rate_norm = median(norm_rates)
    solve_s = round(median(solve_ss), 4)
    breakdown = solver_breakdown()
    # snapshot BEFORE the host/equal-load passes below: their different
    # group counts legitimately hit new buckets, and the gate is about
    # the measured steady-state passes only
    obs = solver_observability(compiles_at_warmup)
    tpu_placed, tpu_nodes = density(h, jobs)

    # host oracle on a sample (to completion)
    hh, hjobs = build_cluster(n_nodes, host_sample, count, constrained)
    host_dt = host_place(hh, hjobs)
    host_rate = len(hjobs) / host_dt
    host_placed, host_nodes = density(hh, hjobs)

    # density parity at EQUAL placed load: TPU solves the SAME sample-sized
    # problem on an identical fresh cluster (this is the ≤1% criterion)
    eh, ejobs = build_cluster(n_nodes, host_sample, count, constrained)
    tpu_place(eh, ejobs, warm=False)
    eq_placed, eq_nodes = density(eh, ejobs)

    # BENCH_TRACE summary BEFORE the attribution pass: the pass drains
    # and clears the global trace recorder for its own span-agreement
    # bookkeeping, which would otherwise destroy this config's measured
    # bench.batch traces (main()'s late trace_summary() would read an
    # empty ring and silently drop the "trace" key)
    tsum = trace_summary()

    # Drop every cluster built above BEFORE the attribution pass: with
    # the trial, host-sample, AND equal-load heaps still alive, every
    # gen2 collection during attribution scanned millions of dead-weight
    # objects (and ran the jax gc callback against them) — measured as
    # the dominant share of the r6 capture's 30% gc_share. Only the
    # density/rate SCALARS are needed past this point.
    h = jobs = hh = hjobs = eh = ejobs = None
    gc.collect()

    # host-attribution pass: where the host second goes, from the
    # always-on profiler (un-measured; follows the rate trials)
    attribution = host_attribution_pass(
        n_nodes, n_jobs, count, constrained,
        wall_target_s=2.0 if min_trial_s > 0 else 1.0,
        max_passes=60,
    )

    host_density = host_placed / max(1, host_nodes)
    eq_density = eq_placed / max(1, eq_nodes)
    ratio = eq_density / max(host_density, 1e-9)
    # the native C++ hot loop gets the same adjacent-burst treatment:
    # vs_native_cpp compares the two CONTROL-NORMALIZED rates, so a
    # load change between the tpu trials and this (later) native run
    # can't fake a ratio move
    ctrl_native_pre = control_burst()
    native = native_baseline(n_nodes, max(n_jobs, 50), count, constrained)
    ctrl_native = (ctrl_native_pre + control_burst()) / 2
    density_ok = ratio >= 0.99
    if not density_ok:
        log(
            f"[{name}] DENSITY GATE FAILED: equal-load ratio {ratio:.4f} "
            f"< 0.99 — the solver packs worse than the host oracle"
        )
    log(
        f"[{name}] control-normalized {tpu_rate_norm:.2f} evals/s "
        f"(spread {spread_pct(norm_rates)}%; adjacent control "
        f"{[round(c / 1e6, 2) for c in ctrl_per_trial]} Munits/s vs ref "
        f"{CONTROL_REF_OPS_S / 1e6:.2f})"
    )
    log(
        f"[{name}] tpu median {tpu_rate:.2f} evals/s over {trials} runs "
        f"x {rounds} passes "
        f"(spread {spread_pct(rates)}%, {tpu_placed} placed); host "
        f"{host_rate:.2f} evals/s over {host_sample} evals ({host_placed} "
        f"placed); equal-load density tpu {eq_density:.2f} vs host "
        f"{host_density:.2f} allocs/node (ratio {ratio:.3f}, "
        f"pass={density_ok}); breakdown {breakdown}; resident sync "
        f"{resident_syncs}"
    )
    log(
        f"[{name}] solver observability: {obs['compiles']} compiles "
        f"({obs['recompiles_after_warmup']} after warmup), "
        f"{obs['cache_hits']} cache hits, mean occupancy "
        f"{obs['mean_occupancy']}, h2d {obs['h2d_bytes']}B / d2h "
        f"{obs['d2h_bytes']}B"
    )
    out = {
        "tpu_evals_per_s": round(tpu_rate, 2),
        "tpu_evals_per_s_runs": [round(r, 2) for r in rates],
        "tpu_spread_pct": spread_pct(rates),
        # the drift-immune headline: per-trial rates normalized by
        # temporally-adjacent control bursts (docs/operations.md
        # "Reading a bench capture"). Raw rates above stay published —
        # they are this box's actual throughput — but only the
        # normalized figure is comparable across captures.
        "control_normalized_evals_per_s": round(tpu_rate_norm, 2),
        "control_normalized_runs": [round(r, 2) for r in norm_rates],
        "control_normalized_spread_pct": spread_pct(norm_rates),
        "control_ref_ops_s": CONTROL_REF_OPS_S,
        "control_ops_s_runs": [round(c) for c in ctrl_per_trial],
        "passes_per_trial": rounds,
        "tpu_solver_internal_s": solve_s,
        "solve_breakdown": breakdown,
        "solver_observability": obs,
        "host_attribution": attribution,
        **({"trace": tsum} if tsum is not None else {}),
        "resident_sync_modes": resident_syncs,
        "host_evals_per_s": round(host_rate, 2),
        "host_sample_evals": host_sample,
        "vs_host": round(tpu_rate / host_rate, 2),
        "tpu_placed": tpu_placed,
        "host_placed": host_placed,
        "equal_load_density_tpu": round(eq_density, 3),
        "equal_load_density_host": round(host_density, 3),
        "equal_load_density_ratio": round(ratio, 4),
        "density_within_1pct": density_ok,
    }
    if native is not None:
        native_norm = (
            native["evals_per_s"] * CONTROL_REF_OPS_S / max(ctrl_native, 1e-9)
        )
        out["native_cpp_evals_per_s"] = native["evals_per_s"]
        out["native_cpp_normalized_evals_per_s"] = round(native_norm, 2)
        out["vs_native_cpp_raw"] = round(
            tpu_rate / max(native["evals_per_s"], 1e-9), 4
        )
        # the PAIRED statistic: both sides normalized by their own
        # adjacent controls — the gated figure
        out["vs_native_cpp"] = round(
            tpu_rate_norm / max(native_norm, 1e-9), 4
        )
        _NATIVE_CAVEAT[0] = True
        log(
            f"[{name}] native C++ hot loop {native['evals_per_s']:.0f} "
            f"evals/s ({native_norm:.0f} control-normalized) -> "
            f"vs_native_cpp {out['vs_native_cpp']} (raw "
            f"{out['vs_native_cpp_raw']})"
        )
    return out


def run_preempt_config():
    """BASELINE config 4: oversubscription → preemption across tiers."""
    from nomad_tpu.scheduler.context import SchedulerConfig

    n_nodes, fill_jobs, fill_count = 500, 25, 180
    hi_jobs, hi_count = 20, 50
    log(
        f"[preempt] {n_nodes} nodes, fill {fill_jobs}x{fill_count} @prio20, "
        f"wave {hi_jobs}x{hi_count} @prio70"
    )
    cfg = SchedulerConfig(preemption_service=True)

    def build():
        h, fills = build_cluster(
            n_nodes, fill_jobs, fill_count, False, priority=20,
            job_prefix="fill", cpu=400, mem=800,
        )
        tpu_place(h, fills, warm=False)  # setup, not measured
        his = add_jobs(h, hi_jobs, hi_count, False, priority=70,
                       job_prefix="hi", cpu=400, mem=800)
        return h, fills, his

    # TPU: one batched preemption solve (priority-tier kernel),
    # median of 3 fresh builds
    rates = []
    h = fills = his = None
    for _ in range(3):
        h = fills = his = None
        gc.collect()
        h, fills, his = build()
        tpu_dt, plans = tpu_place(h, his, cfg)
        rates.append(len(his) / tpu_dt)
    tpu_rate = median(rates)
    tpu_placed, _ = density(h, his)
    tpu_preempted = sum(
        len(v) for p in plans.values() for v in p.node_preemptions.values()
    )

    # host oracle: per-eval preemption scoring, all 20 evals
    hh, _, hhis = build()
    host_dt = host_place(hh, hhis, cfg)
    host_rate = len(hhis) / host_dt
    host_placed, _ = density(hh, hhis)
    host_preempted = sum(
        1
        for p in hh.plans
        for allocs in p.node_preemptions.values()
        for _ in allocs
    )
    log(
        f"[preempt] tpu {tpu_rate:.2f} evals/s, placed {tpu_placed}, "
        f"preempted {tpu_preempted}; host {host_rate:.2f} evals/s, placed "
        f"{host_placed}, preempted {host_preempted}"
    )
    return {
        "tpu_evals_per_s": round(tpu_rate, 2),
        "tpu_evals_per_s_runs": [round(r, 2) for r in rates],
        "tpu_spread_pct": spread_pct(rates),
        "host_evals_per_s": round(host_rate, 2),
        "host_sample_evals": len(hhis),
        "vs_host": round(tpu_rate / host_rate, 2),
        "tpu_placed": tpu_placed,
        "host_placed": host_placed,
        "tpu_preempted": tpu_preempted,
        "host_preempted": host_preempted,
    }


def run_drain_config():
    """BASELINE config 5: mixed service+system under node-drain churn."""
    from nomad_tpu import mock
    from nomad_tpu.scheduler.tpu import solve_eval_batch
    from nomad_tpu.structs import DrainStrategy

    n_nodes, svc_jobs, svc_count, drain_n = 1000, 20, 100, 100
    log(
        f"[drain] {n_nodes} nodes, {svc_jobs}x{svc_count} service + 1 system "
        f"job, drain {drain_n} nodes"
    )

    def build():
        h, svcs = build_cluster(n_nodes, svc_jobs, svc_count, False)
        tpu_place(h, svcs, warm=False)
        sysjob = mock.system_job(id="bench-sys")
        sysjob.datacenters = ["dc1", "dc2", "dc3", "dc4"]
        sysjob.task_groups[0].tasks[0].resources.cpu = 100
        sysjob.task_groups[0].tasks[0].resources.memory_mb = 64
        sysjob.task_groups[0].tasks[0].resources.networks = []
        h.state.upsert_job(h.next_index(), sysjob)
        h.process("system", mock.eval_for_job(sysjob))
        return h, svcs, sysjob

    def drain_nodes(h):
        from nomad_tpu.structs.structs import DesiredTransition

        nodes = h.state.nodes()[:drain_n]
        for n in nodes:
            h.state.update_node_drain(
                h.next_index(), n.id, DrainStrategy(deadline_s=300)
            )
        # The node drainer marks each draining node's allocs for
        # migration (drainer.py / reference drainer/watch_nodes.go);
        # without the marks a drain eval is a no-op and the config
        # measures nothing but reconcile overhead.
        drained = {n.id for n in nodes}
        marks = {
            a.id: DesiredTransition(migrate=True)
            for nid in drained
            for a in h.state.allocs_by_node_terminal(nid, False)
        }
        h.state.update_alloc_desired_transition(h.next_index(), marks, [])
        return drained

    def drain_evals(h, svcs, sysjob, drained):
        from nomad_tpu import mock as m

        evs = []
        for job in svcs:
            if any(
                a.node_id in drained and not a.terminal_status()
                for a in h.state.allocs_by_job(job.namespace, job.id)
            ):
                evs.append(m.eval_for_job(job, triggered_by="node-update"))
        return evs, m.eval_for_job(sysjob, triggered_by="node-update")

    # TPU path: batched solve for services, vectorized system scheduler;
    # median of 3 fresh builds (drain was the noisiest config in r4)
    from nomad_tpu.scheduler.context import SchedulerConfig

    tpu_cfg = SchedulerConfig(backend="tpu")
    rates = []
    h = svcs = sysjob = None
    for _ in range(3):
        h = svcs = sysjob = None
        gc.collect()
        h, svcs, sysjob = build()
        drained = drain_nodes(h)
        evs, sysev = drain_evals(h, svcs, sysjob, drained)
        # warm at post-drain shapes against a throwaway snapshot
        solve_eval_batch(h.snapshot(), h, [mock.eval_for_job(j) for j in svcs])
        t0 = time.perf_counter()
        plans = solve_eval_batch(h.snapshot(), h, evs)
        for ev in evs:
            h.submit_plan(plans[ev.id])
        h.process("system", sysev, tpu_cfg)
        tpu_dt = time.perf_counter() - t0
        rates.append((len(evs) + 1) / tpu_dt)
    n_evals = len(evs) + 1
    tpu_rate = median(rates)
    tpu_placed, _ = density(h, svcs)

    # host path: identical cluster, same drain, host scheduler throughout
    hh, hsvcs, hsysjob = build()
    hdrained = drain_nodes(hh)
    hevs, hsysev = drain_evals(hh, hsvcs, hsysjob, hdrained)
    t0 = time.perf_counter()
    for ev in hevs:
        hh.process("service", ev)
    hh.process("system", hsysev)
    host_dt = time.perf_counter() - t0
    host_rate = (len(hevs) + 1) / host_dt
    host_placed, _ = density(hh, hsvcs)
    log(
        f"[drain] {n_evals} drain evals: tpu {tpu_rate:.2f} evals/s "
        f"({tpu_placed} live), host {host_rate:.2f} evals/s "
        f"({host_placed} live)"
    )
    return {
        "tpu_evals_per_s": round(tpu_rate, 2),
        "tpu_evals_per_s_runs": [round(r, 2) for r in rates],
        "tpu_spread_pct": spread_pct(rates),
        "host_evals_per_s": round(host_rate, 2),
        "host_sample_evals": len(hevs) + 1,
        "vs_host": round(tpu_rate / host_rate, 2),
        "drain_evals": n_evals,
        "tpu_live_after_drain": tpu_placed,
        "host_live_after_drain": host_placed,
    }


def native_baseline(n_nodes, n_evals, count, constrained) -> dict | None:
    """Measured native-code calibration (VERDICT r3 next-round #1b).

    The Go toolchain is absent in this environment, so the reference
    scheduler cannot be built here; bench_native/sched_bench.cc is a
    C++ reimplementation of the host scheduler's per-eval placement
    loop (feasibility + power-of-N-choices + ScoreFitBinPack) measured
    on THIS machine — a compiled-language stand-in with a measured
    basis instead of the former "Go is 30-100x faster" hand-wave. It
    deliberately excludes reconcile/plan-apply/state costs, making the
    native denominator FASTER than a full Go pass and vs_native
    conservative for the TPU side."""
    import hashlib
    import subprocess
    from pathlib import Path

    src = Path(__file__).parent / "bench_native" / "sched_bench.cc"
    if not src.exists():
        return None
    tag = hashlib.sha256(src.read_bytes()).hexdigest()[:12]
    cache = Path(
        os.environ.get("NOMAD_TPU_BIN_DIR")
        or Path.home() / ".cache" / "nomad_tpu" / "bin"
    )
    out = cache / f"nomad-sched-bench-{tag}"
    try:
        if not out.exists():
            cache.mkdir(parents=True, exist_ok=True)
            tmp = str(out) + ".tmp"
            proc = subprocess.run(
                ["g++", "-O2", "-std=c++17", "-o", tmp, str(src)],
                capture_output=True, text=True, timeout=120,
            )
            if proc.returncode != 0:
                log(f"native bench build failed: {proc.stderr[:200]}")
                return None
            os.replace(tmp, out)
        proc = subprocess.run(
            [str(out), str(n_nodes), str(n_evals), str(count),
             "1" if constrained else "0"],
            capture_output=True, text=True, timeout=300,
        )
        if proc.returncode != 0:
            return None
        return json.loads(proc.stdout)
    except (OSError, subprocess.TimeoutExpired, ValueError):
        return None


def run_plan_apply_config():
    """Applier-side throughput at c2m scale (VERDICT r3 next-round #2).

    Solver-produced plans flow plan queue → batched applier (one
    enqueue_batch item: per-node conflict partition → merged verify →
    ONE raft apply with a bulk store transaction; conflicting plans fall
    back serial — plan_apply.py). Reports queue→applied evals/s and its
    ratio to the solver-internal rate; the gate is apply_vs_solve >= 0.6
    on the trial medians so verification never becomes the pipeline's
    bottleneck (reference overlaps these the thread way,
    plan_apply.go:54-63 + plan_apply_pool.go:18).

    Bench hygiene (r5 verdict weak #1 + r7 next-round #3: the gate
    margin sat inside load noise and single-pass trials swung 96.6%
    run-to-run): one un-measured warmup pass sizes the trial — each
    measured trial repeats the solve+apply cycle on fresh clusters
    until it holds >= BENCH_MIN_TRIAL_S (default 20s) of work, so a
    scheduler-tick load spike is amortized instead of being the whole
    sample; 5 trials, gate on the median at apply_vs_solve >= 0.6."""
    from nomad_tpu import mock
    from nomad_tpu.scheduler.tpu import solve_eval_batch
    from nomad_tpu.server.plan_apply import PlanApplier
    from nomad_tpu.server.plan_queue import PlanQueue
    from nomad_tpu.server.raft import FSM, InmemLog

    n_nodes, n_jobs, count = SERVICE_CONFIGS["c2m"][:3]
    trials = max(1, int(os.environ.get("BENCH_PLAN_APPLY_TRIALS", "5")))
    min_trial_s = float(os.environ.get("BENCH_MIN_TRIAL_S", "20"))
    solve_rates, apply_rates, merged_counts = [], [], []
    apply_dts = []
    results = None
    rounds = 1

    from nomad_tpu.gctune import release_frozen_garbage

    pass_no = [0]

    def one_pass():
        """Fresh cluster, one solve + one batched apply; returns the
        timed (solve_dt, apply_dt) with build cost excluded."""
        nonlocal results
        pass_no[0] += 1
        if pass_no[0] % 8 == 0:
            # reclaim the dropped clusters' frozen cycles (see the
            # c2m trial loop) — this config leaks the same ~64MB/pass
            release_frozen_garbage()
        else:
            gc.collect()
        h, jobs = build_cluster(n_nodes, n_jobs, count, constrained=True)
        snap = h.snapshot()
        solve_eval_batch(snap, h, [mock.eval_for_job(j) for j in jobs])
        evals = [mock.eval_for_job(j) for j in jobs]
        t0 = time.perf_counter()
        plans = solve_eval_batch(snap, h, evals)
        solve_dt = time.perf_counter() - t0

        state = h.state
        raft_log = InmemLog(FSM(state), start_index=state.latest_index())
        queue = PlanQueue()
        queue.set_enabled(True)
        applier = PlanApplier(
            queue, state, raft_log.apply, raft_log.apply_async
        )
        applier.start()
        t0 = time.perf_counter()
        futs = queue.enqueue_batch([plans[ev.id] for ev in evals])
        results = [f.result(timeout=300) for f in futs]
        apply_dt = time.perf_counter() - t0
        applier.stop()
        queue.set_enabled(False)
        return solve_dt, apply_dt

    # warmup: jit, codec, allocator pools all hot now — and the pass
    # duration sizes the measured trials to >= min_trial_s of work
    warm_solve, warm_apply = one_pass()
    rounds = max(
        1, int(-(-min_trial_s // max(warm_solve + warm_apply, 1e-9)))
    )
    log(
        f"[plan_apply] {n_nodes} nodes, {n_jobs} plans x {count} allocs: "
        f"warmup pass {warm_solve + warm_apply:.1f}s -> {rounds} "
        f"pass(es)/trial (>= {min_trial_s:.0f}s of work), {trials} trials"
    )
    for _ in range(trials):
        t_solve = t_apply = 0.0
        for _ in range(rounds):
            s_dt, a_dt = one_pass()
            t_solve += s_dt
            t_apply += a_dt
        solve_rates.append(rounds * n_jobs / t_solve)
        apply_rates.append(rounds * n_jobs / t_apply)
        apply_dts.append(t_apply / rounds)
        from nomad_tpu import metrics as _metrics

        s = _metrics.snapshot()["samples"].get(
            "nomad.plan_apply.batch_merged"
        )
        merged_counts.append(int(s["last"]) if s else 0)
    applied = sum(
        len(v) for r in results for v in r.node_allocation.values()
    )
    apply_rate = median(apply_rates)
    solve_rate = median(solve_rates)
    ratio = apply_rate / solve_rate
    breakdown = solver_breakdown()
    # the queue->applied wall time of one whole batch IS the commit
    # stage here (the worker records nomad.tpu.commit_seconds live)
    breakdown["commit_s"] = round(median(apply_dts), 4)
    log(
        f"[plan_apply] solve median {solve_rate:.2f} evals/s, apply "
        f"median {apply_rate:.2f} evals/s over {trials} trials x "
        f"{rounds} passes (spread {spread_pct(apply_rates)}%, {applied} "
        f"allocs committed/pass, {merged_counts} plans merged/batch), "
        f"apply/solve {ratio:.2f} on medians (pass={ratio >= 0.6}); "
        f"breakdown {breakdown}"
    )
    return {
        "apply_evals_per_s": round(apply_rate, 2),
        "apply_evals_per_s_runs": [round(r, 2) for r in apply_rates],
        "apply_spread_pct": spread_pct(apply_rates),
        "solve_evals_per_s": round(solve_rate, 2),
        "solve_evals_per_s_runs": [round(r, 2) for r in solve_rates],
        "passes_per_trial": rounds,
        "min_trial_s": min_trial_s,
        "apply_vs_solve": round(ratio, 3),
        "allocs_committed": applied,
        "plans_merged_per_batch": merged_counts,
        "stage_breakdown": breakdown,
        "apply_vs_solve_ge_0_6": ratio >= 0.6,
    }


class _MiniServer:
    """Just enough server for a TPUBatchWorker: broker + queue + applier
    + raft-backed state (the real Server wires identically). Shared by
    the pipeline and smoke_interactive configs."""

    def __init__(self, state):
        from nomad_tpu.server.eval_broker import EvalBroker
        from nomad_tpu.server.plan_apply import PlanApplier
        from nomad_tpu.server.plan_queue import PlanQueue
        from nomad_tpu.server.raft import FSM, InmemLog

        self.state = state
        self.fsm = FSM(state)
        self.log = InmemLog(self.fsm, start_index=state.latest_index())
        self.eval_broker = EvalBroker()
        self.eval_broker.set_enabled(True)
        self.plan_queue = PlanQueue()
        self.plan_queue.set_enabled(True)
        self.plan_applier = PlanApplier(
            self.plan_queue, state, self.raft_apply, self.raft_apply_async
        )
        self.plan_applier.start()
        # partial-commit retry evals must re-enqueue (the real Server's
        # FSM side channel) or a worker could silently drop conflicted
        # work and look faster than it is
        self.fsm.on_eval_update = self._on_eval_update

    def _on_eval_update(self, evals):
        for ev in evals:
            if ev.should_enqueue():
                self.eval_broker.enqueue(ev)

    def raft_apply(self, msg_type, payload):
        return self.log.apply(msg_type, payload)

    def raft_apply_async(self, msg_type, payload):
        return self.log.apply_async(msg_type, payload)

    def shutdown(self):
        self.plan_applier.stop()
        self.plan_queue.set_enabled(False)
        self.eval_broker.set_enabled(False)


def run_pipeline_config():
    """Solve/commit overlap proof (round-6 tentpole acceptance): with a
    simulated 0.15s device round-trip injected into every dense solve
    (SchedulerConfig.inject_device_latency_s — the RTT measured through
    the axon tunnel in r4/r5), the two-stage TPUBatchWorker must beat
    the non-overlapped solve-then-commit loop on the same workload by
    >= 1.5x. This is the evidence VERDICT r5 item #2 called testable
    without the chip: batch N+1's dequeue/lower/device dispatch runs
    while batch N's plans materialize and commit."""
    from nomad_tpu import mock
    from nomad_tpu.scheduler.context import SchedulerConfig
    from nomad_tpu.scheduler.tpu import solve_eval_batch
    from nomad_tpu.server.worker import TPUBatchWorker

    n_nodes = int(os.environ.get("BENCH_PIPE_NODES", "2000"))
    # 64 jobs x 300 allocs = 60% fill of the 2k-node cluster across 8
    # batches — enough batches that pipeline fill/drain doesn't
    # dominate, and per-batch host work comparable to the injected RTT
    # so the overlap (not the GIL floor) is what's measured
    n_jobs = int(os.environ.get("BENCH_PIPE_JOBS", "64"))
    count = int(os.environ.get("BENCH_PIPE_COUNT", "300"))
    batch_size = int(os.environ.get("BENCH_PIPE_BATCH", "8"))
    latency = float(os.environ.get("BENCH_INJECT_LATENCY_S", "0.15"))
    log(
        f"[pipeline] {n_nodes} nodes, {n_jobs} jobs x {count} allocs, "
        f"batches of {batch_size}, injected device RTT {latency}s"
    )

    def run_once(pipeline: bool) -> float:
        gc.collect()
        h, jobs = build_cluster(n_nodes, n_jobs, count, False)
        cfg = SchedulerConfig(
            backend="tpu", inject_device_latency_s=latency
        )
        # warm the jit cache at the per-batch shapes, un-measured
        warm_cfg = SchedulerConfig(backend="tpu")
        solve_eval_batch(
            h.snapshot(), h,
            [mock.eval_for_job(j) for j in jobs[:batch_size]], warm_cfg,
        )
        srv = _MiniServer(h.state)
        worker = TPUBatchWorker(
            srv, batch_size=batch_size, config=cfg, pipeline=pipeline
        )
        for job in jobs:
            srv.eval_broker.enqueue(mock.eval_for_job(job))

        def all_placed():
            # end-to-end completion: every job's allocs COMMITTED, not
            # just evals acked — retries (if any) are paid, not dropped
            for job in jobs:
                live = sum(
                    1
                    for a in h.state.allocs_by_job(job.namespace, job.id)
                    if not a.terminal_status()
                )
                if live < count:
                    return False
            return True

        t0 = time.perf_counter()
        worker.start()
        deadline = t0 + 600
        # coarse poll: all_placed() walks every job's allocs under the
        # GIL, so a tight poll steals cycles from the very overlap being
        # measured
        while not all_placed() and time.perf_counter() < deadline:
            time.sleep(0.05)
        dt = time.perf_counter() - t0
        done = all_placed()
        worker.stop()
        srv.shutdown()
        if not done:
            log(f"[pipeline] WARNING: workload incomplete after {dt:.0f}s")
            incomplete[0] += 1
        return n_jobs / dt

    incomplete = [0]
    piped, serial = [], []
    for _ in range(3):
        piped.append(run_once(pipeline=True))
        serial.append(run_once(pipeline=False))
    piped_rate, serial_rate = median(piped), median(serial)
    # the verdict is the MEDIAN OF TEMPORALLY-ADJACENT PAIR RATIOS, not
    # a ratio of medians: both comparator sides drift together over a
    # full-capture run (shared-host co-tenancy — the round-13 overhead
    # gate's measured finding), and pairing cancels exactly the drift
    # that cross-run medians pair badly against
    pair_ratios = [p / max(s, 1e-9) for p, s in zip(piped, serial)]
    ratio = median(pair_ratios)
    # Gate re-based 1.5 -> 1.3 with the round-16 device-model fix: the
    # injected RTT is now a SERIALLY-BUSY queue (one modeled chip —
    # solver._inject_rtt), where the old model let two in-flight
    # batches' windows overlap like a second device and the measured
    # ratio rode that to 1.74-1.82. Under the honest model the ideal
    # ratio is (host + rtt) / max(host, rtt); for this config's shape
    # (host ~0.09s, rtt 0.15s) that ceiling is ~1.6, and the gate holds
    # the measured overlap at >= ~80% of it. ideal_overlap_ratio is
    # published per run so the gate's headroom is always visible.
    host_s = max(n_jobs / max(serial_rate, 1e-9) / (n_jobs / batch_size)
                 - latency, 1e-9)
    ideal = (host_s + latency) / max(host_s, latency)
    # Gate re-based again (r10): >= 0.8 x the IN-RUN ideal, which is
    # what the 1.3 bar always encoded (0.8 x the then-current ~1.6
    # ceiling). A static bar punishes host-side speedups: faster host
    # passes shrink host_s, the ceiling falls toward 1 (less host work
    # to hide under the RTT), and the fixed 1.3 ends up ABOVE the
    # theoretical maximum. Gating on the fraction-of-ideal keeps the
    # claim ("the overlap machinery hides most of what is hideable")
    # invariant under host-phase perf changes.
    ok = ratio >= 0.8 * ideal and incomplete[0] == 0
    log(
        f"[pipeline] pipelined {piped_rate:.2f} evals/s (spread "
        f"{spread_pct(piped)}%) vs non-overlapped {serial_rate:.2f} "
        f"(spread {spread_pct(serial)}%) -> overlap ratio {ratio:.2f} "
        f"(pairs {[round(r, 2) for r in pair_ratios]}, ideal "
        f"{ideal:.2f} under the serialized device model, pass={ok})"
    )
    return {
        "pipelined_evals_per_s": round(piped_rate, 2),
        "pipelined_runs": [round(r, 2) for r in piped],
        "pipelined_spread_pct": spread_pct(piped),
        "non_overlapped_evals_per_s": round(serial_rate, 2),
        "non_overlapped_runs": [round(r, 2) for r in serial],
        "non_overlapped_spread_pct": spread_pct(serial),
        "injected_device_latency_s": latency,
        "incomplete_runs": incomplete[0],
        "overlap_ratio": round(ratio, 3),
        "overlap_pair_ratios": [round(r, 3) for r in pair_ratios],
        "ideal_overlap_ratio": round(ideal, 3),
        "overlap_ge_0_8_ideal": ok,
    }


# The r08 capture of record's smoke single-eval wall (1 / 220.38
# evals/s, BENCH_r08.json): the basis of the smoke_interactive_p50 gate
# — the interactive fast path must land a single eval in at most HALF
# this, measured with the same solve+submit methodology.
R08_SMOKE_EVAL_S = 1.0 / 220.38

# Control-workload yardstick (the drift-immune c2m verdict): units/s of
# control_burst() on this box measured near-idle at r10 calibration
# time, the same pin-a-constant discipline as R08_SMOKE_EVAL_S. This
# box's background co-tenancy drifts the measured host throughput
# +/-40% across captures on UNCHANGED code (r07->r09 re-measured 122.3
# -> 113.3 -> 79.9); the control bursts ride temporally adjacent to
# every measured trial, so each trial's normalized rate cancels the
# load that slowed both — the r13 paired-adjacent-ratio recipe that
# already made the pipeline-overlap and interactive gates load-proof.
# Pinned from each leg's best observed steady rate on this box (LCG
# 9.9 Mops/s, 128MB sweep 15.5ms): ref = total units / (lcg_s + mem_s)
# at those healths. The box's effective CPU speed itself drifts ~40%
# across hour windows (LCG alone read 6.9 and 9.9 Mops/s on the same
# idle box) — which is WHY rates gate on the paired-control statistic.
CONTROL_REF_OPS_S = 524_000_000.0
# Two legs sized ~equal near-idle, matching the measured pass's mix:
#   interpreter leg — integer LCG, register-only (zero memory traffic):
#     tracks interpreter/ALU throughput, which the host-side scheduler
#     phases ride on. ~0.4s.
#   memory leg — repeated full sweeps of a fixed 128MB buffer: tracks
#     memory-subsystem bandwidth, which the XLA solve phase rides on.
#     An ALU-only control is BLIND to co-tenant cache/bandwidth
#     pressure (measured in the first r10 attempt: device phase slowed
#     17% while the LCG leg slowed 2%) — this leg slows with it. ~0.4s.
CONTROL_LCG_OPS = 4_000_000
CONTROL_MEM_SWEEPS = 24
CONTROL_MEM_WORDS = 16_777_216  # int64 words: one 128MB sweep
_CONTROL_SINK = [0]
_CONTROL_BUF: list = [None]


def control_burst() -> float:
    """Fixed two-leg in-run control workload — deterministic work, no
    jax/device touch — as a yardstick for the interpreter AND
    memory-subsystem throughput every measured pass rides on. ~0.8s per
    burst: long enough that OS scheduling jitter stays ~2% (0.2s bursts
    measured 20-40% swings). Returns units/s (units = LCG ops + summed
    words, a fixed constant); a trial's control-normalized rate is
    raw * CONTROL_REF_OPS_S / (mean of its two adjacent bursts)."""
    buf = _CONTROL_BUF[0]
    if buf is None:
        buf = _CONTROL_BUF[0] = np.arange(CONTROL_MEM_WORDS, dtype=np.int64)
    x = 1
    acc = 0
    t0 = time.perf_counter()
    for _ in range(CONTROL_LCG_OPS):
        x = (x * 1103515245 + 12345) & 0xFFFFFFFF
    for _ in range(CONTROL_MEM_SWEEPS):
        acc += int(buf.sum())
    dt = time.perf_counter() - t0
    _CONTROL_SINK[0] = x ^ acc  # defeat a hypothetical dead-code elision
    return (CONTROL_LCG_OPS + CONTROL_MEM_SWEEPS * CONTROL_MEM_WORDS) / dt


def run_smoke_interactive_config():
    """Interactive single-eval latency, three views (ISSUE 15 tentpole
    yardstick):

      direct — N fresh-cluster single-eval passes (solve via the host
        microsolve + plan submit), the SAME methodology the r08 smoke
        capture used. Gate: p50 <= R08_SMOKE_EVAL_S / 2 — the "2x
        single-eval latency" acceptance, apples to apples.
      lane (unloaded) — the full worker stack (broker -> priority lane
        -> microsolve -> plan applier -> raft), one eval at a time:
        what a quiet cluster's `job register` actually pays end to end.
      lane (loaded) — the same stack while a mega-batch stream (with
        the modeled 0.15s device RTT) saturates the worker: the
        priority lane must keep interactive p50 far below the batch
        cadence. Gate: loaded interactive p50 <= 1/4 of the batch
        lane's p50 — without the lane an interactive eval rides a mega
        batch and pays exactly that batch p50.

    The per-stage milliseconds (dispatch / micro / submit / commit
    p50s) are published in remaining_ms_p50 — the round-12 profiler's
    naming of where the interactive millisecond goes — and every
    nomad.worker.lane.* counter lands in the payload."""
    from nomad_tpu import metrics as _metrics
    from nomad_tpu import mock
    from nomad_tpu.scheduler.context import SchedulerConfig
    from nomad_tpu.scheduler.tpu import ResidentClusterState, solve_eval_batch
    from nomad_tpu.server.worker import TPUBatchWorker

    direct_passes = int(os.environ.get("BENCH_IA_DIRECT", "30"))
    lane_evals = int(os.environ.get("BENCH_IA_LANE", "30"))
    loaded_probes = int(os.environ.get("BENCH_IA_LOADED", "16"))
    latency = float(os.environ.get("BENCH_INJECT_LATENCY_S", "0.15"))
    log(
        f"[smoke_interactive] {direct_passes} direct passes, "
        f"{lane_evals} unloaded + {loaded_probes} loaded lane evals, "
        f"mega-batch RTT {latency}s"
    )

    def wait_live(h, job, want, deadline_s=30.0):
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < deadline_s:
            live = sum(
                1
                for a in h.state.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()
            )
            if live >= want:
                return True
            time.sleep(0.0002)
        return False

    # -- direct: the r08-methodology single-eval wall -------------------
    gc.collect()
    direct = []
    h, jobs = build_cluster(10, 1, 10, False)
    resident = ResidentClusterState()
    tpu_place(h, jobs, warm=False, resident=resident)  # warm caches
    for i in range(direct_passes):
        h = jobs = None
        h, jobs = build_cluster(10, 1, 10, False)
        resident = ResidentClusterState()
        dt, _ = tpu_place(h, jobs, resident=resident)
        direct.append(dt)
    direct_p50 = median(direct)
    micro_s = _metrics.snapshot()["samples"].get("nomad.tpu.micro_seconds")
    direct_used_micro = bool(micro_s and micro_s.get("count"))

    # -- lane, unloaded: full worker stack, one eval at a time ----------
    h, jobs = build_cluster(10, 1, 10, False)
    srv = _MiniServer(h.state)
    worker = TPUBatchWorker(
        srv, batch_size=8, config=SchedulerConfig(backend="tpu")
    )
    worker.start()
    unloaded = []
    ia_jobs = add_jobs(h, lane_evals, 1, False, priority=70,
                       job_prefix="ia-quiet")
    for job in ia_jobs:
        t0 = time.perf_counter()
        srv.eval_broker.enqueue(mock.eval_for_job(job))
        ok = wait_live(h, job, 1)
        unloaded.append(time.perf_counter() - t0)
        if not ok:
            log(f"[smoke_interactive] WARNING: {job.id} never placed")
    worker.stop()
    srv.shutdown()
    unloaded_p50 = median(unloaded[2:] or unloaded)

    # -- lane, loaded: interactive probes against a mega-batch stream --
    gc.collect()
    h, mega = build_cluster(400, 24, 100, False)
    cfg = SchedulerConfig(backend="tpu", inject_device_latency_s=latency)
    # warm the jit cache at the mega-batch shapes, un-measured
    solve_eval_batch(
        h.snapshot(), h,
        [mock.eval_for_job(j) for j in mega[:8]],
        SchedulerConfig(backend="tpu"),
    )
    srv = _MiniServer(h.state)
    worker = TPUBatchWorker(srv, batch_size=8, config=cfg)
    worker.start()
    for job in mega:
        srv.eval_broker.enqueue(mock.eval_for_job(job))
    loaded = []
    ia2 = add_jobs(h, loaded_probes, 2, False, priority=70,
                   job_prefix="ia-loaded")
    time.sleep(0.3)  # let the mega stream occupy the pipeline first
    for job in ia2:
        t0 = time.perf_counter()
        srv.eval_broker.enqueue(mock.eval_for_job(job))
        ok = wait_live(h, job, 2)
        loaded.append(time.perf_counter() - t0)
        if not ok:
            log(f"[smoke_interactive] WARNING: {job.id} never placed")
        time.sleep(0.05)
    # drain the mega stream so the batch-lane histogram is complete
    deadline = time.perf_counter() + 300
    while time.perf_counter() < deadline:
        done = all(
            sum(
                1
                for a in h.state.allocs_by_job(j.namespace, j.id)
                if not a.terminal_status()
            ) >= 100
            for j in mega
        )
        if done:
            break
        time.sleep(0.05)
    worker.stop()
    srv.shutdown()
    loaded_p50 = median(loaded)

    snap = _metrics.snapshot()
    samples = snap["samples"]
    counters = snap["counters"]
    batch_s = samples.get("nomad.worker.lane.batch_seconds") or {}
    batch_p50 = batch_s.get("p50")
    # where the interactive millisecond goes (profiler/stage naming)
    remaining = {}
    for key, name in (
        ("nomad.tpu.batch_dispatch_seconds", "dispatch"),
        ("nomad.tpu.micro_seconds", "micro_solve"),
        ("nomad.plan.submit_seconds", "plan_submit"),
        ("nomad.tpu.commit_seconds", "commit"),
        ("nomad.broker.wait_seconds", "broker_wait"),
    ):
        s = samples.get(key)
        if s and s.get("count"):
            remaining[name] = round(s["p50"] * 1e3, 3)
    lanes = {
        k.rsplit(".", 1)[1]: int(v)
        for k, v in counters.items()
        if k.startswith("nomad.worker.lane.")
    }
    p50_gate = direct_p50 <= R08_SMOKE_EVAL_S / 2
    lane_gate = (
        batch_p50 is not None and loaded_p50 <= 0.25 * batch_p50
    )
    log(
        f"[smoke_interactive] direct p50 {direct_p50 * 1e3:.2f}ms (gate "
        f"<= {R08_SMOKE_EVAL_S / 2 * 1e3:.2f}ms, pass={p50_gate}); lane "
        f"unloaded p50 {unloaded_p50 * 1e3:.2f}ms; loaded p50 "
        f"{loaded_p50 * 1e3:.2f}ms vs batch p50 "
        f"{(batch_p50 or 0) * 1e3:.0f}ms (pass={lane_gate}); lanes "
        f"{lanes}; remaining ms {remaining}"
    )
    return {
        # headline: single evals per second at the direct p50
        "tpu_evals_per_s": round(1.0 / max(direct_p50, 1e-9), 2),
        "single_eval_p50_s": round(direct_p50, 6),
        "single_eval_runs_ms": [round(d * 1e3, 3) for d in direct],
        "single_eval_spread_pct": spread_pct(direct),
        "r08_single_eval_s": round(R08_SMOKE_EVAL_S, 6),
        "direct_used_micro": direct_used_micro,
        "lane_unloaded_p50_s": round(unloaded_p50, 6),
        "lane_loaded_p50_s": round(loaded_p50, 6),
        "lane_loaded_runs_ms": [round(d * 1e3, 3) for d in loaded],
        "batch_lane_p50_s": round(batch_p50, 6) if batch_p50 else None,
        "lane_counters": lanes,
        "remaining_ms_p50": remaining,
        "injected_device_latency_s": latency,
        "smoke_interactive_p50_ok": bool(p50_gate),
        "smoke_interactive_lane_ok": bool(lane_gate),
    }


def run_soak_config():
    """Sustained-traffic soak: closed-loop mixed traffic (job
    register/scale/stop, dispatch, node churn) against a live 3-server
    durable cluster under a SEEDED FaultPlane schedule (rpc drops, lost
    responses, slow fsync, device faults, a partition/heal cycle), with
    the overload controls engaged — bounded broker admission,
    per-namespace RPC rate limits, plan-queue backpressure
    (nomad_tpu/testing/loadgen.py run_soak).

    Unlike every other config, this one runs WITH faults injected by
    design: the claim under test is graceful degradation, and its gates
    (invariants hold, p99 bounded, admission engaged) are only
    meaningful under fault load. The chaos tripwire still applies to
    the PERF configs — the soak installs its plane for its own run and
    uninstalls it before returning.

    Env knobs: BENCH_SOAK_S (duration, default 30; the slow-tier run
    uses 600), BENCH_SOAK_RATE (target offered eval arrival rate/s —
    size it at >= 10x the capture-of-record c2m steady rate for the
    acceptance run), BENCH_SOAK_SEED, BENCH_SOAK_P99_S (e2e p99 bound),
    BENCH_SOAK_DEPTH (broker admission depth)."""
    import shutil
    import tempfile

    from nomad_tpu.testing.loadgen import run_soak

    duration = float(os.environ.get("BENCH_SOAK_S", "30"))
    rate = float(os.environ.get("BENCH_SOAK_RATE", "120"))
    seed = int(os.environ.get("BENCH_SOAK_SEED", "42"))
    p99_bound = float(os.environ.get("BENCH_SOAK_P99_S", "15"))
    depth = int(os.environ.get("BENCH_SOAK_DEPTH", "96"))
    log(
        f"[soak] {duration:.0f}s at {rate:.0f} evals/s offered, seed "
        f"{seed}, admission depth {depth}, faults ON"
    )
    root = tempfile.mkdtemp(prefix="nomad-tpu-soak-")
    try:
        report = run_soak(
            root,
            duration_s=duration,
            rate=rate,
            seed=seed,
            admission_depth=depth,
            namespace_cap=max(8, depth // 2),
            blocked_cap=depth,
            rpc_rate=float(os.environ.get("BENCH_SOAK_RPC_RATE", "40")),
            rpc_burst=float(os.environ.get("BENCH_SOAK_RPC_BURST", "80")),
            use_tpu_worker=True,
            partition_cycle=True,
            p99_bound_s=p99_bound,
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)
    c = report["counters"]
    log(
        f"[soak] offered {report['offered']} ({report['offered_rate_per_s']}"
        f"/s), accepted {report['accepted']}, client-throttled "
        f"{report['throttled_client_visible']}; shed "
        f"{c['nomad.broker.shed']}, rejected {c['nomad.broker.rejected']}, "
        f"throttled http {c['nomad.http.throttled']} rpc "
        f"{c['nomad.rpc.throttled']}, backpressure "
        f"{c['nomad.worker.backpressure_throttled']}; e2e "
        f"{report.get('e2e_seconds')}; converged {report['converged']}, "
        f"invariants {report['invariants_ok']}"
        + (f" ({report['invariant_error']})" if report["invariant_error"] else "")
        + f", faults fired {report['fired_faults']}"
    )
    cpu = report.get("server_cpu") or {}
    src = report.get("source_attribution") or {}
    log(
        f"[soak] server cpu {cpu.get('cpu_seconds')}s "
        f"({cpu.get('per_node_cpu_fraction')} cores/node over "
        f"{cpu.get('node_count')} nodes); source attribution "
        f"coverage {src.get('coverage')} over {src.get('total_calls')} "
        f"calls, top {src.get('top')}"
    )
    # flight-recorder verdict (docs/incidents.md): the soak runs with
    # faults ON, so captured incidents are signal, not failure — the
    # capture line makes "did the blackbox see what the fault plane
    # did" auditable from the bench JSON alone
    from nomad_tpu import blackbox as _bb

    rec = _bb.recorder()
    report["blackbox"] = rec.stats()
    report["incidents"] = [
        {"id": r["id"], "reason": r["reason"]} for r in rec.incidents()
    ]
    bstats = report["blackbox"]
    log(
        f"[soak] blackbox: {int(bstats['journal_recorded'])} journal "
        f"rows ({int(bstats['journal_evicted'])} evicted), triggers "
        f"fired {int(bstats['triggers_fired'])} (deduped "
        f"{int(bstats['triggers_deduped'])}), incidents captured "
        f"{int(bstats['incidents_captured'])}"
        + (
            " " + ",".join(r["reason"] for r in report["incidents"])
            if report["incidents"] else ""
        )
    )
    return report


def run_fleet_config():
    """Fleet-scale survival (ROADMAP fleet-scale item): a simulated
    client fleet — real registration/heartbeat/alloc-watch RPCs
    multiplexed over a cooperative driver pool
    (nomad_tpu/testing/fleet.py) — held against a live cluster through
    a registration storm, steady state, a mass partition (heartbeat
    wheel expiry storm → batched down-marks), and a mass reconnect
    (node door admission + register batcher).

    Gates: the whole fleet registers through the admission door; every
    silent victim is down-marked within its TTL bound; the reconnect
    storm recovers; BOTH storms commit node-status raft entries in
    coalesced batches (entries <= victims / min_avg_batch); heartbeat
    RPC p99 stays bounded THROUGH the storms; server CPU per node per
    second stays under the soak gate; chaos invariants hold.

    Env knobs: BENCH_FLEET_NODES (default 5000 — the acceptance run's
    floor), BENCH_FLEET_S (steady-state seconds, default 600 for the
    acceptance run's 10-minute hold), BENCH_FLEET_SEED,
    BENCH_FLEET_SERVERS, BENCH_FLEET_TTL_S, BENCH_FLEET_P99_S,
    BENCH_FLEET_CPU_PER_NODE, BENCH_FLEET_DRIVERS,
    BENCH_FLEET_FRACTION (partition fraction)."""
    import shutil
    import tempfile

    from nomad_tpu.testing.fleet import run_fleet_scale

    n_nodes = int(os.environ.get("BENCH_FLEET_NODES", "5000"))
    steady = float(os.environ.get("BENCH_FLEET_S", "600"))
    seed = int(os.environ.get("BENCH_FLEET_SEED", "42"))
    n_servers = int(os.environ.get("BENCH_FLEET_SERVERS", "1"))
    ttl = float(os.environ.get("BENCH_FLEET_TTL_S", "10"))
    log(
        f"[fleet] {n_nodes} nodes on {n_servers} server(s), "
        f"{steady:.0f}s steady, ttl {ttl:.0f}s, seed {seed}"
    )
    root = tempfile.mkdtemp(prefix="nomad-tpu-fleet-")
    try:
        report = run_fleet_scale(
            root,
            seed=seed,
            n_servers=n_servers,
            n_nodes=n_nodes,
            steady_s=steady,
            heartbeat_ttl_s=ttl,
            driver_threads=int(os.environ.get("BENCH_FLEET_DRIVERS", "8")),
            real_watchers=8,
            partition_fraction=float(
                os.environ.get("BENCH_FLEET_FRACTION", "0.2")
            ),
            register_deadline_s=max(60.0, n_nodes / 50.0),
            rate=float(os.environ.get("BENCH_FLEET_RATE", "10")),
            p99_bound_s=float(os.environ.get("BENCH_FLEET_P99_S", "1.0")),
            cpu_per_node_bound=float(
                os.environ.get("BENCH_FLEET_CPU_PER_NODE", "0.002")
            ),
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)
    cpu = report["server_cpu"]
    log(
        f"[fleet] registered {report['fleet']['registered']}/{n_nodes} "
        f"in {report['populate_s']}s ({report['register_throttled']:.0f} "
        f"throttles); victims {report['victims']}: down in "
        f"{report['expiry_detect_s']}s over {report['expire_batches']:.0f} "
        f"batches (avg {report['avg_expiry_batch']}), reconnect in "
        f"{report['reconnect_s']}s over {report['reconnect_batches']:.0f} "
        f"entries (avg {report['avg_reconnect_batch']}); hb p99 "
        f"{report['hb_p99_s']}s; cpu/node "
        f"{cpu['per_node_cpu_fraction']} cores; converged "
        f"{report['converged']}, invariants {report['invariants_ok']}"
        + (
            f" ({report['invariant_error']})"
            if report["invariant_error"]
            else ""
        )
    )
    return report


SERVICE_CONFIGS = {
    # name: (nodes, jobs, count/job, constrained, host_sample >= 20
    #        except smoke, which has a single job by definition)
    "smoke": (10, 1, 10, False, 1),
    "c1k": (1000, 50, 100, False, 20),
    "c2m": (10000, 100, 1000, True, 20),
}

SHARDED_CAVEAT_TEXT = (
    "c2m_sharded's device phase uses the injected-latency model (the "
    "pipeline config's precedent): per-mesh device time is "
    "BENCH_SHARDED_RTT_S x (shard rows / total rows), the scaling a "
    "real mesh's LOCAL phase has by construction. The 8 'devices' here "
    "are XLA virtual CPU devices sharing this box's cores, so raw "
    "fallback wall cannot strong-scale; the gate is still a real "
    "regression bound — the CPU-fallback kernel compute and host "
    "phases run inside the modeled budget, so a sharded kernel whose "
    "per-device work stops shrinking (e.g. a replicated full-sort "
    "waterfill) blows the D=8 budget and fails the gate"
)


def run_c2m_sharded_config():
    """c2m-scale solve with the node axis sharded over a device mesh:
    100k+ nodes split over 8 virtual devices, solved end-to-end through
    the production mesh path (SchedulerConfig.mesh_devices → SolverMesh
    top-k kernels + NamedSharding resident tensors + delta syncs).

    Measures eval throughput at mesh sizes 1 and 8 on the SAME sharded
    code and problem. The device phase rides the injected-latency model
    (SHARDED_CAVEAT_TEXT): latency = BENCH_SHARDED_RTT_S x (1/D), the
    linear local-phase scaling real hardware provides; the CPU-fallback
    kernel's real compute is the FLOOR under the model (solver._inject_rtt
    sleeps from dispatch, compute proceeds async), so the published
    sharded_scaling only reaches the gate when per-device work + host
    overhead genuinely fit the shrinking budget.

    sharded_scaling = (rate_D8 / rate_D1) / 8, where rate is the
    PIPELINED end-to-end eval throughput: rounds run through the same
    two-phase overlap as the production TPUBatchWorker
    (solve_eval_batch_begin of batch N+1 overlaps batch N's device
    wait; consecutive batches chain on the in-flight used' tensor, with
    the chain composing with the resident shards), so throughput is
    bounded by max(host phase, device phase) — and scales with the mesh
    exactly while the device phase dominates.
    """
    from nomad_tpu import solverobs
    from nomad_tpu.gctune import freeze_resident_heap, paused_gc
    from nomad_tpu import mock
    from nomad_tpu.scheduler.context import SchedulerConfig
    from nomad_tpu.scheduler.tpu import (
        ResidentClusterState,
        solve_eval_batch_begin,
    )
    from nomad_tpu.scheduler.tpu.sharding import solver_mesh

    n_nodes = int(os.environ.get("BENCH_SHARDED_NODES", "100000"))
    n_jobs = int(os.environ.get("BENCH_SHARDED_JOBS", "16"))
    count = int(os.environ.get("BENCH_SHARDED_COUNT", "500"))
    base_rtt = float(os.environ.get("BENCH_SHARDED_RTT_S", "10.0"))
    rounds = int(os.environ.get("BENCH_SHARDED_ROUNDS", "6"))
    settle = int(os.environ.get("BENCH_SHARDED_SETTLE", "2"))
    device_counts = (1, 8)
    log(
        f"[c2m_sharded] {n_nodes} nodes, {n_jobs} jobs x {count}, mesh "
        f"sizes {device_counts}, device model base {base_rtt}s x 1/D, "
        f"{rounds} pipelined rounds"
    )

    def run_rounds(h, cfg, resident, rounds_jobs, syncs=None):
        """Pipelined steady-state rounds (the TPUBatchWorker overlap,
        inline): begin(N+1) runs while batch N's device work is in
        flight; N+1 chains on N's used' so the batches place
        conflict-free; finish(N) + submit then completes N. Returns the
        per-round completion walls (batch N's submit to batch N+1's) —
        the medianable steady-state cadence."""
        prev = None
        walls = []
        t0 = t_last = time.perf_counter()
        with paused_gc(freeze_on_exit=True):
            for jobs in rounds_jobs:
                snap = h.snapshot()
                evals = [mock.eval_for_job(j) for j in jobs]
                chain = prev[0].chain if prev is not None else None
                pend = solve_eval_batch_begin(
                    snap, h, evals, cfg, resident=resident,
                    used_chain=chain,
                )
                if syncs is not None:
                    syncs.append(
                        f"{resident.last_sync}"
                        + ("+chain" if pend.chain_accepted else "")
                    )
                if prev is not None:
                    p_pend, p_evals = prev
                    plans = p_pend.finish()
                    for ev in p_evals:
                        h.submit_plan(plans[ev.id])
                    now = time.perf_counter()
                    walls.append(now - t_last)
                    t_last = now
                prev = (pend, evals)
            p_pend, p_evals = prev
            plans = p_pend.finish()
            for ev in p_evals:
                h.submit_plan(plans[ev.id])
            now = time.perf_counter()
            walls.append(now - t_last)
        return time.perf_counter() - t0, walls

    per_mesh = {}
    recompiles_after_warmup = 0
    for d in device_counts:
        mesh = solver_mesh(d)
        cfg = SchedulerConfig(
            small_batch_threshold=0,
            mesh_devices=d,
            inject_device_latency_s=base_rtt / d,
        )
        gc.collect()
        h, warm_jobs = build_cluster(
            n_nodes, n_jobs, count, False, job_prefix=f"shard{d}-warm"
        )
        freeze_resident_heap()
        resident = ResidentClusterState(mesh=mesh)
        # warm rounds WITHOUT the latency model (compiles don't sleep):
        # THREE rounds so the steady-state machinery compiles too before
        # anything is measured — round 2 consumes the chain, round 3
        # ships the first delta-sync scatter (the full sync happens at
        # round 1, and round 2's diff is clean because round 1 is still
        # in flight at its begin)
        import copy as _copy

        warm_cfg = _copy.copy(cfg)
        warm_cfg.inject_device_latency_s = 0.0
        warm_s, _ = run_rounds(h, warm_cfg, resident, [
            warm_jobs,
            add_jobs(h, n_jobs, count, False, job_prefix=f"shard{d}-w2"),
            add_jobs(h, n_jobs, count, False, job_prefix=f"shard{d}-w3"),
        ])
        compiles0 = solverobs.compiles()
        syncs: list = []
        rounds_jobs = [
            add_jobs(h, n_jobs, count, False, job_prefix=f"shard{d}-r{r}")
            for r in range(settle + rounds)
        ]
        wall, walls = run_rounds(h, cfg, resident, rounds_jobs, syncs=syncs)
        recompiles_after_warmup += solverobs.compiles() - compiles0
        # steady-state cadence: the settle rounds absorb pipeline fill
        # and the executable's first-runs transient; the median of the
        # rest is the per-round completion interval one load spike
        # cannot own
        steady = walls[settle:] if len(walls) > settle + 1 else walls
        round_s = median(steady)
        rate = n_jobs / round_s
        per_mesh[d] = {
            "devices": d,
            "injected_device_s": round(base_rtt / d, 4),
            "warm_s": round(warm_s, 2),
            "rounds": rounds,
            "wall_s": round(wall, 3),
            "round_walls_s": [round(w, 3) for w in walls],
            "steady_round_s": round(round_s, 3),
            "evals_per_s": round(rate, 3),
            "spread_pct": spread_pct(steady),
            "resident_sync_modes": syncs,
        }
        log(
            f"[c2m_sharded] D={d}: {rate:.3f} evals/s (steady round "
            f"{round_s:.2f}s, walls {[round(w, 2) for w in walls]}), "
            f"syncs {syncs}, injected {base_rtt / d:.3f}s"
        )
        h = warm_jobs = rounds_jobs = None
    obs = solver_observability()
    obs["recompiles_after_warmup"] = recompiles_after_warmup
    d1, d8 = device_counts[0], device_counts[-1]
    scaling = (
        per_mesh[d8]["evals_per_s"]
        / max(per_mesh[d1]["evals_per_s"], 1e-9)
    ) / (d8 / d1)
    shards = (obs.get("sharding") or {}).get("last_shards") or []
    mean_shard_occ = (
        round(
            sum(s["occupancy"] for s in shards) / len(shards), 4
        )
        if shards else None
    )
    log(
        f"[c2m_sharded] scaling {scaling:.3f} x linear (gate >= 0.7); "
        f"mean shard occupancy {mean_shard_occ}; allgather "
        f"{obs['allgather_bytes']}B, scatter {obs['scatter_bytes']}B, "
        f"recompiles after warmup {recompiles_after_warmup}"
    )
    return {
        "tpu_evals_per_s": per_mesh[d8]["evals_per_s"],
        "per_mesh": {str(k): v for k, v in per_mesh.items()},
        "sharded_scaling": round(scaling, 4),
        "sharded_scaling_linear_gate": 0.7,
        "device_model_base_rtt_s": base_rtt,
        "mean_shard_occupancy": mean_shard_occ,
        "solver_observability": obs,
        "caveat": SHARDED_CAVEAT_TEXT,
    }


POOL_CAVEAT_TEXT = (
    "c2m_pool models each solver-pool member as a RemoteSolver with its "
    "OWN SchedulerConfig under the injected-latency device model "
    "(docs/solver-pool.md): the serially-busy `_device_free_at` queue is "
    "per-config, so every member is an independent chip exactly as a "
    "real pool member's device is. Members share one state store (the "
    "perfectly-synced-replica limit — production replicas trail by a "
    "raft beat, which the warm loop's delta sync bounds), so the ratio "
    "isolates PLACEMENT-PLANE capacity: it proves the dispatch fan-out "
    "and per-member resident state scale, not the replication fabric."
)


def run_c2m_pool_config():
    """Solver-pool horizontal-scaling bench (docs/solver-pool.md): the
    same c2m-shaped eval stream dispatched to a pool of 1 vs 2 warm
    RemoteSolver members, each an independent serially-busy chip under
    the injected-latency model. Gates committed-eval throughput at
    >= 1.5x from one member to two.

    The drive loop mirrors the leader's TPUBatchWorker dispatch: each
    mega-batch goes to a pool member on its own thread (the SolverPool
    dispatch-thread idiom), the 'leader' submits plan columns as batches
    land, and up to pool-size batches stay in flight. A single member
    serializes batches on its solve lock + device window; two members
    overlap two batches — the ratio IS the placement-plane scaling.

    Drift-normalized (the c2m verdict discipline): pool sizes interleave
    ABBA within one process, so this box's co-tenancy drift hits both
    sides equally and the RATIO is trustworthy even when raw rates are
    not. Each trial rebuilds cluster state fresh so trial N's accumulated
    allocs never tax trial N+1's snapshots asymmetrically."""
    import queue as _queue
    import threading as _threading

    from nomad_tpu.gctune import freeze_resident_heap, paused_gc
    from nomad_tpu import mock
    from nomad_tpu.scheduler.context import SchedulerConfig
    from nomad_tpu.scheduler.tpu.remote_solve import RemoteSolver

    n_nodes = int(os.environ.get("BENCH_POOL_NODES", "2000"))
    n_jobs = int(os.environ.get("BENCH_POOL_JOBS", "8"))
    count = int(os.environ.get("BENCH_POOL_COUNT", "100"))
    rtt = float(os.environ.get("BENCH_POOL_RTT_S", "0.8"))
    n_batches = int(os.environ.get("BENCH_POOL_BATCHES", "6"))
    pairs = int(os.environ.get("BENCH_POOL_PAIRS", "2"))
    pool_sizes = (1, 2)
    gate = float(os.environ.get("BENCH_POOL_SCALING_GATE", "1.5"))
    log(
        f"[c2m_pool] {n_nodes} nodes, {n_batches} batches of {n_jobs} "
        f"jobs x {count}, pool sizes {pool_sizes}, device model "
        f"{rtt}s/batch per member, {pairs} interleaved trial pairs"
    )

    class _Host:
        """RemoteSolver host duck-type: the bench's shared store stands
        in for every member's raft replica (POOL_CAVEAT_TEXT)."""

        def __init__(self, state):
            self.state = state

    def run_trial(pool_size: int) -> float:
        """One trial: fresh cluster, fresh members, one unmeasured warm
        batch per member (compile + full resident sync), then n_batches
        dispatched round-robin with pool_size in flight. Returns
        committed evals/s over the measured window."""
        gc.collect()
        h, _ = build_cluster(
            n_nodes, n_jobs, count, False, job_prefix=f"pool{pool_size}-warm"
        )
        freeze_resident_heap()
        host = _Host(h.state)
        members = [
            RemoteSolver(
                host,
                config=SchedulerConfig(
                    backend="tpu",
                    small_batch_threshold=0,
                    inject_device_latency_s=rtt,
                ),
                node_id=f"bench-m{i}",
            )
            for i in range(pool_size)
        ]
        # warm OUTSIDE the injected-latency model: one batch per member
        # compiles the kernels (first trial only — the jit cache is
        # process-wide) and takes the full resident upload, so every
        # measured batch rides the delta-sync path on a warm replica
        for i, m in enumerate(members):
            m.config.inject_device_latency_s = 0.0
            warm_jobs = add_jobs(
                h, n_jobs, count, False, job_prefix=f"pool{pool_size}-w{i}"
            )
            warm_evals = [mock.eval_for_job(j) for j in warm_jobs]
            out = m.solve(warm_evals, h.snapshot().index, timeout_s=60.0)
            for ev in warm_evals:
                h.submit_plan(out["plans"][ev.id])
            m.config.inject_device_latency_s = rtt
        batches = [
            [
                mock.eval_for_job(j)
                for j in add_jobs(
                    h, n_jobs, count, False,
                    job_prefix=f"pool{pool_size}-b{b}",
                )
            ]
            for b in range(n_batches)
        ]
        min_index = h.snapshot().index
        done_q: _queue.Queue = _queue.Queue()

        def dispatch(i: int, member, evals) -> None:
            try:
                done_q.put((i, member.solve(
                    evals, min_index, timeout_s=rtt * n_batches + 60.0
                ), None))
            except Exception as e:  # noqa: BLE001 - surfaced on the drive loop
                done_q.put((i, None, e))

        t0 = time.perf_counter()
        with paused_gc(freeze_on_exit=True):
            next_b = 0
            in_flight = 0
            completed = 0
            while completed < n_batches:
                # keep pool_size batches in flight, round-robin — the
                # least-in-flight pick SolverPool makes degenerates to
                # round-robin under uniform batch cost
                while next_b < n_batches and in_flight < pool_size:
                    _threading.Thread(
                        target=dispatch,
                        args=(next_b, members[next_b % pool_size],
                              batches[next_b]),
                        name=f"bench-pool-dispatch-{next_b}",
                        daemon=True,
                    ).start()
                    next_b += 1
                    in_flight += 1
                i, out, err = done_q.get()
                if err is not None:
                    raise err
                # the 'leader' commits: plan columns apply on the
                # authoritative store, exactly RemotePendingBatch.finish
                for ev in batches[i]:
                    h.submit_plan(out["plans"][ev.id])
                in_flight -= 1
                completed += 1
        wall = time.perf_counter() - t0
        rate = (n_batches * n_jobs) / wall
        assert all(m.warmups == 1 for m in members), (
            "pool members must warm exactly once, before measurement"
        )
        log(
            f"[c2m_pool] pool={pool_size}: {rate:.3f} evals/s "
            f"({n_batches} batches in {wall:.2f}s, member solves "
            f"{[m.solves for m in members]}, syncs "
            f"{[m.last_sync for m in members]})"
        )
        return rate

    # ABBA interleave: linear host drift cancels between the sides
    order: list = []
    for p in range(pairs):
        order.extend(pool_sizes if p % 2 == 0 else pool_sizes[::-1])
    rates: dict = {s: [] for s in pool_sizes}
    for size in order:
        rates[size].append(run_trial(size))
    per_pool = {
        str(s): {
            "members": s,
            "trial_evals_per_s": [round(r, 3) for r in rates[s]],
            "evals_per_s": round(median(rates[s]), 3),
            "spread_pct": spread_pct(rates[s]),
        }
        for s in pool_sizes
    }
    s1, s2 = pool_sizes
    scaling = per_pool[str(s2)]["evals_per_s"] / max(
        per_pool[str(s1)]["evals_per_s"], 1e-9
    )
    log(
        f"[c2m_pool] scaling {scaling:.3f}x from {s1} -> {s2} members "
        f"(gate >= {gate})"
    )
    return {
        "tpu_evals_per_s": per_pool[str(s2)]["evals_per_s"],
        "per_pool": per_pool,
        "pool_scaling": round(scaling, 4),
        "pool_scaling_gate": gate,
        "device_model_rtt_s": rtt,
        "caveat": POOL_CAVEAT_TEXT,
    }


def _run_sharded_subprocess() -> dict:
    """Run the c2m_sharded config in a child process so ITS backend can
    be forced to 8 virtual devices without the parent paying for it:
    `xla_force_host_platform_device_count` partitions the CPU client
    across the virtual devices and slows every single-chip config
    (measured: the c2m device phase 0.19s -> 2.1s per batch with the
    flag process-wide). The child is this same script with
    BENCH_CONFIG=c2m_sharded; its JSON line carries the config block
    (latency_percentiles and solver_observability included) and is
    spliced into the parent's results verbatim."""
    import subprocess

    env = dict(os.environ)
    env["BENCH_CONFIG"] = "c2m_sharded"
    env.setdefault("BENCH_SKIP_TPU_PROBE", "1")  # parent probed already
    env.pop("BENCH_STRICT", None)  # parent owns the exit-code policy
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=2400,
    )
    for raw in proc.stderr.splitlines():
        log(raw)
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    if proc.returncode != 0 or not lines:
        raise RuntimeError(
            f"c2m_sharded subprocess failed rc={proc.returncode}: "
            f"{proc.stderr[-2000:]}"
        )
    payload = json.loads(lines[-1])
    return payload["configs"]["c2m_sharded"]


def _ensure_device() -> dict:
    """Guard against an unreachable TPU wedging the whole bench run.

    The axon tunnel has been observed to hang jax device init
    indefinitely; probe it in a SUBPROCESS with a hard timeout and, on
    failure, fall back to CPU with an explicit flag so the output is
    never silently mislabeled. Returns {"platform", "tpu_available"}."""
    import subprocess

    if os.environ.get("BENCH_SKIP_TPU_PROBE"):
        return {"platform": "as-configured", "tpu_available": None}
    timeout_s = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "240"))
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        platform = (proc.stdout or "").strip().lower()
        # a CPU-only jax init "succeeds" — that is exactly the silent
        # mislabeling this probe exists to prevent
        ok = proc.returncode == 0 and platform not in ("", "cpu")
    except subprocess.TimeoutExpired:
        ok = False
    if ok:
        return {"platform": "tpu", "tpu_available": True}
    log(
        f"WARNING: TPU device init failed/timed out after {timeout_s}s; "
        f"falling back to CPU — TPU throughput is higher than these "
        f"numbers"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    return {"platform": "cpu-fallback", "tpu_available": False}


def main():
    # Fault-injection tripwire: a capture taken while chaos knobs are
    # live (NOMAD_TPU_INJECT_* env vars, or an installed FaultPlane)
    # measures the injected faults, not the system — it must never be
    # certifiable. The payload still prints (debugging under injection
    # is legitimate) but every gate is forced to fail.
    from nomad_tpu import faultplane as _chaos

    chaos_knobs = _chaos.env_knobs_active()
    if chaos_knobs:
        log(
            f"CHAOS INJECTION ACTIVE ({', '.join(chaos_knobs)}): "
            f"this capture CANNOT gate — results are fault-distorted"
        )
    sel = os.environ.get("BENCH_CONFIG", "all")
    if sel == "c2m_sharded":
        # the sharded config needs 8 (virtual) devices; must be set
        # before the jax backend initializes. ONLY for the solo run:
        # the full run executes this config in a subprocess instead
        # (_run_sharded_subprocess) because the flag costs the
        # single-chip configs ~40% — XLA partitions the CPU client
        # across the virtual devices (measured: c2m 122 -> 70 evals/s
        # with the flag process-wide).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    device = _ensure_device()
    # always-on host profiler: runs through every measured pass (the
    # production posture — the overhead gate in tests/test_hostobs.py
    # holds it >= 0.95x unprofiled) and feeds each config's
    # host_attribution block
    from nomad_tpu import hostobs as _hostobs

    _hostobs.start()
    if os.environ.get("BENCH_TRACE"):
        # per-batch span emission through the production tracing
        # subsystem (trace.py); each config's critical-path summary
        # lands under its result's "trace" key
        from nomad_tpu import trace as _trace

        _trace.configure(max_traces=256, enabled_=True)
    names = (
        ["smoke", "smoke_interactive", "c1k", "c2m", "c2m_sharded",
         "c2m_pool", "preempt", "drain", "plan_apply", "pipeline",
         "soak"]
        if sel == "all"
        else [sel]
    )
    results = {}
    for name in names:
        # per-config histogram baseline: the registry accumulates
        # process-wide, so reset between configs keeps each config's
        # latency_percentiles attributable to its own passes
        from nomad_tpu import metrics as _metrics
        from nomad_tpu import solverobs as _solverobs

        _metrics.registry().reset()
        # fresh observatory too: compile/transfer counts stay
        # attributable per config (the jit cache itself stays warm —
        # cross-config cache hits are real and correctly counted)
        _solverobs._install(_solverobs.SolverObservatory())
        # fresh host-profiler ledgers for the same reason
        _hostobs.reset_stats()
        if name in SERVICE_CONFIGS:
            n_nodes, n_jobs, count, constrained, sample = SERVICE_CONFIGS[name]
            results[name] = run_service_config(
                name, n_nodes, n_jobs, count, constrained, sample,
                # c2m: >= 20s of work per trial, median of 5 (VERDICT
                # r7 next-round #3 — the 96.6%-spread fix)
                min_trial_s=(
                    float(os.environ.get("BENCH_MIN_TRIAL_S", "20"))
                    if name == "c2m" else 0.0
                ),
                trials=5 if name == "c2m" else 3,
            )
        elif name == "c2m_sharded":
            if sel == "all":
                # subprocess: its own 8-virtual-device backend, already
                # carrying latency_percentiles/solver_observability —
                # the parent's registry never saw its passes
                results[name] = _run_sharded_subprocess()
                continue
            results[name] = run_c2m_sharded_config()
        elif name == "c2m_pool":
            results[name] = run_c2m_pool_config()
        elif name == "smoke_interactive":
            results[name] = run_smoke_interactive_config()
        elif name == "preempt":
            results[name] = run_preempt_config()
        elif name == "drain":
            results[name] = run_drain_config()
        elif name == "plan_apply":
            results[name] = run_plan_apply_config()
        elif name == "pipeline":
            results[name] = run_pipeline_config()
        elif name == "soak":
            results[name] = run_soak_config()
        elif name == "fleet":
            results[name] = run_fleet_config()
        else:
            raise SystemExit(f"unknown BENCH_CONFIG {name}")
        results[name]["latency_percentiles"] = latency_percentiles()
        # every config carries the solver_observability block; service
        # configs computed theirs at the warmup boundary already
        results[name].setdefault(
            "solver_observability", solver_observability()
        )
        tsum = trace_summary()
        if tsum is not None:
            results[name]["trace"] = tsum

    headline = "c2m" if "c2m" in results else names[0]
    hl = results[headline]
    # Explicit gates (VERDICT r4 weak #5): a density regression or an
    # applier falling behind the solver must fail LOUDLY, not hide in a
    # sub-key. Every gate that exists in this run must pass.
    gates = {}
    for cname, r in results.items():
        if "density_within_1pct" in r:
            gates[f"{cname}_density"] = bool(r["density_within_1pct"])
        if "apply_vs_solve_ge_0_6" in r:
            gates[f"{cname}_apply_vs_solve_0_6"] = bool(
                r["apply_vs_solve_ge_0_6"]
            )
        if "overlap_ge_0_8_ideal" in r:
            gates[f"{cname}_overlap_0_8_ideal"] = bool(r["overlap_ge_0_8_ideal"])
        # interactive fast-path gates (ISSUE 15): single-eval p50 at
        # most half the r08 capture's, and the priority lane keeping
        # loaded interactive latency far under the mega-batch cadence
        if "smoke_interactive_p50_ok" in r:
            gates["smoke_interactive_p50"] = bool(
                r["smoke_interactive_p50_ok"]
            )
            gates["smoke_interactive_lane"] = bool(
                r["smoke_interactive_lane_ok"]
            )
        # recompile-bound regression guard (shape-bucketing contract,
        # kernels.py): after the warmup pass, steady-state batches in
        # the smoke and c2m configs must trigger ZERO compiles
        so = r.get("solver_observability") or {}
        if (
            cname in ("smoke", "c2m", "c2m_sharded")
            and "recompiles_after_warmup" in so
        ):
            gates[f"{cname}_recompile_bound"] = (
                so["recompiles_after_warmup"] == 0
            )
        # sharded-solver linear-scaling gate (docs/sharding.md): the
        # mesh path's throughput from 1 -> 8 devices must hold >= 0.7x
        # linear under the per-shard device model
        if "sharded_scaling" in r:
            gates["sharded_scaling"] = (
                r["sharded_scaling"] >= r["sharded_scaling_linear_gate"]
            )
            # resident tensors upload once: after each mesh's first
            # ("full") sync, steady rounds must ship delta scatters or
            # nothing — a mid-run "full" is a resident re-upload
            gates[f"{cname}_delta_only"] = not any(
                mode.startswith("full")
                for mesh in r["per_mesh"].values()
                for mode in mesh["resident_sync_modes"][1:]
            )
        # solver-pool horizontal-scaling gate (docs/solver-pool.md):
        # committed-eval throughput from 1 -> 2 warm pool members must
        # hold >= 1.5x under the per-member serially-busy device model;
        # drift-normalized by the config's ABBA trial interleave
        if "pool_scaling" in r:
            gates["pool_scaling"] = (
                r["pool_scaling"] >= r["pool_scaling_gate"]
            )
        # drift-immune throughput gates (ISSUE 16): both gate on the
        # PAIRED control-normalized statistic, never the raw rate —
        # this box's co-tenancy drifts raw rates +/-40% across captures
        # on unchanged code, so a raw-rate gate can fake both a win and
        # a regression. Floors are env-tunable for slower boxes.
        if cname == "c2m" and "control_normalized_evals_per_s" in r:
            gates["c2m_target_rate"] = r[
                "control_normalized_evals_per_s"
            ] >= float(os.environ.get("BENCH_C2M_TARGET", "250"))
        if cname == "c2m" and "vs_native_cpp" in r:
            gates["c2m_vs_native_cpp"] = r["vs_native_cpp"] >= float(
                os.environ.get("BENCH_VS_NATIVE_FLOOR", "0.25")
            )
        # host-attribution gates (the host-profiling layer's acceptance
        # criteria): named (span x function) sites must cover >= 80% of
        # measured host wall on the c2m config, and the profiler's
        # span-correlated self-times must agree with the traces'
        # stack-self-times within 15% on every span >= 20% of wall
        ha = r.get("host_attribution") or {}
        if cname == "c2m" and "coverage" in ha:
            gates["c2m_host_coverage"] = ha["coverage"] >= 0.8
            gates["c2m_span_agreement"] = bool(ha["span_agreement_ok"])
            # GC-tax ceiling (ISSUE 12): with the post-warmup resident
            # freeze + pipeline-wide paused sections, GC pauses must
            # stay a rounding error of c2m wall. BENCH_GC_SHARE tunes
            # the ceiling; 5% default (pre-fix captures measured the
            # jax gc callback alone at 16.5-17%).
            gates["c2m_gc_share"] = ha["gc_share"] <= float(
                os.environ.get("BENCH_GC_SHARE", "0.05")
            )
        # soak gates: graceful degradation under the seeded fault
        # schedule — safety invariants hold, e2e p99 stays bounded,
        # and admission control demonstrably engaged (nonzero
        # shed/reject/throttle counts)
        if "invariants_ok" in r:
            gates[f"{cname}_invariants"] = bool(
                r["invariants_ok"] and r["converged"]
            )
            gates[f"{cname}_p99_bounded"] = bool(r["p99_bounded"])
            gates[f"{cname}_admission_engaged"] = bool(
                r["admission_engaged"]
            )
        # cluster-observability gates (clusterobs.py): server CPU per
        # simulated node stays bounded (the ROADMAP fleet-scale gate,
        # measurable per-run now) and per-source attribution covers
        # the served handler seconds — fan-out cost is ATTRIBUTABLE,
        # not just bounded
        if "server_cpu" in r:
            bound = float(
                os.environ.get("BENCH_SOAK_CPU_PER_NODE", "0.5")
            )
            gates[f"{cname}_cpu_per_node_bounded"] = (
                r["server_cpu"]["per_node_cpu_fraction"] <= bound
            )
        if "source_attribution" in r:
            gates[f"{cname}_source_coverage"] = (
                r["source_attribution"]["coverage"] >= 0.8
            )
        # fleet-scale survival gates (nomad_tpu/testing/fleet.py): the
        # storm phases complete inside their bounds, and both mass
        # transitions commit node-status raft writes in coalesced
        # batches — the "entries <= constant x batches" claim
        if "reconnect_batched" in r:
            gates[f"{cname}_survival"] = bool(
                r["registered_all"]
                and r["expiry_detected"]
                and r["reconnect_recovered"]
            )
            gates[f"{cname}_raft_batched"] = bool(
                r["expiry_batched"] and r["reconnect_batched"]
            )
            gates[f"{cname}_cpu_per_node"] = bool(r["cpu_bounded"])
    if chaos_knobs:
        # refuse to gate: an injected-fault run can never certify
        gates["no_chaos_injection"] = False
    gates_ok = all(gates.values())
    if not gates_ok:
        log(f"BENCH GATES FAILED: {gates}")
    print(
        json.dumps(
            {
                "metric": f"{headline}_scheduler_throughput",
                # headline = the drift-immune statistic when the config
                # measured one (raw rates ride in configs.*)
                "value": hl.get(
                    "control_normalized_evals_per_s",
                    hl.get("tpu_evals_per_s", hl.get("apply_evals_per_s")),
                ),
                "unit": "evals/sec",
                "vs_baseline": hl.get("vs_host", hl.get("apply_vs_solve")),
                "configs": results,
                "gates": gates,
                "gates_pass": all(gates.values()),
                "chaos_injection_active": chaos_knobs,
                "loadavg": list(os.getloadavg()),
                "platform": device["platform"],
                "tpu_available": device["tpu_available"],
                "caveats": CAVEATS
                + ([NATIVE_CAVEAT_TEXT] if _NATIVE_CAVEAT[0] else [])
                + (
                    [SHARDED_CAVEAT_TEXT]
                    if "c2m_sharded" in results else []
                ),
            }
        )
    )
    # BENCH_STRICT=1: fail the PROCESS on a gate regression (CI usage).
    # Default stays exit-0 so harnesses that capture the JSON line keep
    # working; the gates ride in the payload either way.
    if not gates_ok and os.environ.get("BENCH_STRICT"):
        sys.exit(2)


if __name__ == "__main__":
    main()
